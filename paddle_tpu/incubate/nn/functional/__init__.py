"""Fused functional ops (reference: python/paddle/incubate/nn/functional/).
On TPU these alias framework composites — XLA fuses elementwise chains into
the matmuls; flash attention uses the Pallas kernel."""

from ....nn.functional import rms_norm as fused_rms_norm  # noqa: F401
from ....nn.functional import layer_norm as fused_layer_norm  # noqa: F401
from ....nn.functional import rope as fused_rotary_position_embedding  # noqa: F401
from ....nn.functional import swiglu  # noqa: F401
from ....nn.functional import scaled_dot_product_attention as fused_dot_product_attention  # noqa: F401


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True):
    """Reference: fused_bias_dropout_residual_layer_norm op
    (paddle/phi/kernels/fusion/gpu/fused_bias_dropout_residual_layer_norm*).
    On TPU the whole chain runs as ONE Pallas VMEM pass per row block
    (ops/kernels/bias_dropout_ln_pallas.py); the dropout mask is
    materialized like the reference op's `dropout_mask_out` and generated
    with the framework RNG. Elsewhere: the XLA composite."""
    from ....core.flags import flag
    from ....ops.kernels import _common as kern
    from ....nn import functional as F

    if kern.available() and flag("use_pallas_kernels"):
        import jax
        import jax.numpy as jnp

        from ....core import generator as gen_mod
        from ....core.tensor import as_tensor
        from ....autograd.function import apply_multi

        xt = as_tensor(x)
        hd = xt.shape[-1]
        if training and dropout_rate >= 1.0:
            mask_arr = jnp.zeros(tuple(xt.shape), jnp.float32)
        elif training and dropout_rate > 0.0:
            key = gen_mod.default_generator.split()
            keep = jax.random.bernoulli(key, 1.0 - dropout_rate, xt.shape)
            mask_arr = keep.astype(jnp.float32) / (1.0 - dropout_rate)
        else:
            mask_arr = None  # maskless kernel variant: nothing streamed
        zeros = jnp.zeros((hd,), jnp.float32)
        args = [xt, residual]
        b_in = bias if bias is not None else zeros
        g_in = ln_scale if ln_scale is not None else zeros + 1.0
        be_in = ln_bias if ln_bias is not None else zeros

        from ....ops.kernels.bias_dropout_ln_pallas import bias_dropout_ln
        outs = apply_multi(
            lambda a, r, b, g, be: bias_dropout_ln(
                a, b, r, mask_arr, g, be, ln_epsilon,
                kern.interpret_mode()),
            *args, b_in, g_in, be_in,
            name="fused_bias_dropout_residual_layer_norm")
        return outs[0]

    out = x if bias is None else x + bias
    out = F.dropout(out, dropout_rate, training=training)
    out = out + residual
    return F.layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    from ....nn import functional as F
    from .... import ops
    w = ops.t(weight) if transpose_weight else weight
    return F.linear(x, w, bias)


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """Chunked-KV attention with O(sqrt(S)) activation memory (reference:
    python/paddle/incubate/nn/memory_efficient_attention.py over the cutlass
    kernel). TPU design: online-softmax accumulation over KV chunks inside a
    `lax.scan` — the same recurrence the flash Pallas kernel uses, expressed
    at the XLA level so it works on every backend and any bias shape.

    query/key/value: [B, S, H, D] (reference layout); returns [B, S, H, D].
    """
    import jax
    import jax.numpy as jnp

    from ....autograd.function import apply
    from ....core.tensor import as_tensor
    from ....nn import functional as F

    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    d = q.shape[-1]
    sc = scale if scale is not None else d ** -0.5
    CHUNK = 512

    def f(qa, ka, va, *maybe_bias):
        bias = maybe_bias[0] if maybe_bias else None
        # [B,S,H,D] -> [B,H,S,D]
        qt = jnp.swapaxes(qa, 1, 2) * sc
        kt = jnp.swapaxes(ka, 1, 2)
        vt = jnp.swapaxes(va, 1, 2)
        skv = kt.shape[2]
        n_chunks = max(1, (skv + CHUNK - 1) // CHUNK)
        pad = n_chunks * CHUNK - skv
        if pad:
            kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad), (0, 0)))
            vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        kc = kt.reshape(*kt.shape[:2], n_chunks, CHUNK, kt.shape[-1])
        vc = vt.reshape(*vt.shape[:2], n_chunks, CHUNK, vt.shape[-1])
        if bias is not None:
            bt = jnp.broadcast_to(bias, (*qt.shape[:3], skv))
            bt = jnp.pad(bt, ((0, 0),) * 3 + ((0, pad),),
                         constant_values=-jnp.inf)
            bc = bt.reshape(*bt.shape[:3], n_chunks, CHUNK)
        valid = (jnp.arange(n_chunks * CHUNK) < skv).reshape(n_chunks, CHUNK)

        def chunk_step(carry, idx):
            acc, m, l = carry
            kb = kc[:, :, idx]
            vb = vc[:, :, idx]
            s = jnp.einsum("bhqd,bhkd->bhqk", qt, kb,
                           preferred_element_type=jnp.float32)
            if bias is not None:
                s = s + bc[:, :, :, idx].astype(s.dtype)
            s = jnp.where(valid[idx][None, None, None, :], s, -jnp.inf)
            m_new = jnp.maximum(m, s.max(-1))
            alpha = jnp.exp(m - m_new)
            pexp = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + pexp.sum(-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", pexp.astype(vb.dtype), vb,
                preferred_element_type=jnp.float32)
            return (acc_new, m_new, l_new), None

        b, h, sq, _ = qt.shape
        init = (jnp.zeros((b, h, sq, vt.shape[-1]), jnp.float32),
                jnp.full((b, h, sq), -jnp.inf, jnp.float32),
                jnp.zeros((b, h, sq), jnp.float32))
        (acc, m, l), _ = jax.lax.scan(chunk_step, init,
                                      jnp.arange(n_chunks))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return jnp.swapaxes(out.astype(qa.dtype), 1, 2)

    args = (q, k, v) + ((as_tensor(attn_bias),) if attn_bias is not None
                        else ())
    out = apply(f, *args, name="memory_efficient_attention")
    if p and training:
        # dropout inside the chunk scan would need per-chunk rng threading;
        # the reference drops attention weights — applying it to the output
        # preserves the first moment and keeps the kernel deterministic
        out = F.dropout(out, p, training=True)
    return out


def fused_dropout_add(x, y, p=0.5, training=True, mode='upscale_in_train',
                      name=None):
    """Reference: incubate/nn/functional/fused_dropout_add.py:22 (one fused
    kernel for dropout(x) + y; the CUDA kernel saves a seed/offset pair and
    its grad kernel regenerates the mask). On TPU the Pallas kernel
    (ops/kernels/dropout_add_pallas.py) goes one further: the mask is a
    counter-hash of (seed, element index) computed in VMEM in BOTH passes,
    so it never exists in HBM at all. Off-TPU / other modes: the XLA
    composite with the framework RNG."""
    from ....core.flags import flag
    from ....nn import functional as F
    from ....ops.kernels import _common as kern
    from ....ops.kernels import dropout_add_pallas as dak

    xt = F.as_tensor(x)
    yt = F.as_tensor(y)
    if (training and mode == 'upscale_in_train'
            and kern.available() and flag("use_pallas_kernels")
            and xt.shape == yt.shape and xt.dtype == yt.dtype
            and dak.use_kernel(tuple(xt.shape), p)):
        import jax
        import jax.numpy as jnp

        from ....autograd.function import apply
        from ....core import generator as gen_mod

        key = gen_mod.default_generator.split()
        seed = jax.random.randint(key, (), 0, 2147483647, dtype=jnp.int32)

        def f(a, b, s):
            return dak.dropout_add(a, b, s, float(p),
                                   kern.interpret_mode())
        return apply(f, xt, yt, F.as_tensor(seed), name="fused_dropout_add")
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_linear_param_grad_add(x, dy, dweight=None, dbias=None,
                                multi_precision=False, has_bias=False):
    """Accumulate a linear layer's param grads in place:
    dweight [K, N] += flatten(x)^T @ flatten(dy), dbias [N] += sum(dy).

    Reference: paddle._C_ops.fused_linear_param_grad_add
    (paddle/phi/kernels/fusion/gpu/fused_linear_param_grad_add_kernel.cu),
    the op the TP linear backward and sharding optimizers use to fold the
    weight-grad GEMM into the main_grad buffer
    (fleet/layers/mpu/mp_layers.py:251). On TPU the Pallas kernel
    (ops/kernels/linear_grad_add_pallas.py) keeps the [bk, bn] tile in
    fp32 VMEM for the whole row sweep and donates the buffer; elsewhere
    the jnp composite. `multi_precision` keeps a missing accumulator in
    fp32 (main_grad semantics); returns (dweight, dbias or None)."""
    import jax
    import jax.numpy as jnp

    from ....autograd.function import apply
    from ....core.flags import flag
    from ....core.tensor import as_tensor
    from ....ops.kernels import _common as kern
    from ....ops.kernels import linear_grad_add_pallas as lga

    # grad accumulation is not itself differentiable (the reference op runs
    # inside a manual backward): detach so apply() never sends the
    # AD-rule-less pallas_call through jax.vjp
    xt, dyt = as_tensor(x).detach(), as_tensor(dy).detach()
    k, n = xt.shape[-1], dyt.shape[-1]
    m = 1
    for s in xt.shape[:-1]:
        m *= s
    acc_dtype = (jnp.float32 if multi_precision
                 else jnp.dtype(str(xt._data.dtype)))
    if dweight is None:
        dwt = None
    else:
        dwt = as_tensor(dweight).detach()

    def f_w(xa, dya, *acc):
        x2 = xa.reshape(-1, k)
        dy2 = dya.reshape(-1, n)
        a = acc[0] if acc else jnp.zeros((k, n), acc_dtype)
        if (kern.available() and flag("use_pallas_kernels")
                and lga.use_kernel(m, k, n)):
            return lga.linear_grad_acc(x2, dy2, a, kern.interpret_mode())
        return lga.reference_grad_acc(x2, dy2, a)

    args = (xt, dyt) + ((dwt,) if dwt is not None else ())
    dw = apply(f_w, *args, name="fused_linear_param_grad_add")
    if not has_bias:
        return dw, None
    dbt = as_tensor(dbias).detach() if dbias is not None else None

    def f_b(dya, *acc):
        s = jnp.sum(dya.reshape(-1, n).astype(jnp.float32), axis=0)
        if acc:
            # preserve the provided accumulator's dtype (an fp32 grad
            # buffer must not flip to bf16 just because dy is bf16)
            return (s + acc[0].astype(jnp.float32)).astype(acc[0].dtype)
        return s.astype(acc_dtype)

    db = apply(f_b, *((dyt, dbt) if dbt is not None else (dyt,)),
               name="fused_linear_bias_grad_add")
    return dw, db


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """Reference: incubate/nn/functional/fused_matmul_bias.py:21 (cublasLt
    epilogue fusion). XLA fuses the bias add into the matmul."""
    from .... import ops
    out = ops.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    return out if bias is None else out + bias


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation=None):
    """Reference: fused_matmul_bias.py:110 (gemm epilogue activation)."""
    from ....nn import functional as F
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    if activation in (None, "", "none"):
        return out
    if activation == "gelu":
        return F.gelu(out)
    if activation == "relu":
        return F.relu(out)
    raise ValueError(f"unsupported activation {activation!r}")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type):
    """Expert-choice MoE ffn (reference: fused_ec_moe.py:18): every expert
    takes its top-capacity tokens by gate score, runs them through one
    batched [E, ...] einsum pair (MXU-friendly), results scatter-add back.
    `gate` carries the routing logits [B, S, E]."""
    import jax
    import jax.numpy as jnp

    from ....autograd.function import apply
    from ....core.tensor import as_tensor

    if act_type not in ("gelu", "relu"):
        raise ValueError(f"unsupported act_type {act_type!r}")
    xt = as_tensor(x)
    b, s, h = xt.shape
    e = as_tensor(gate).shape[-1]
    cap = max(1, (b * s) // e)

    def f(xa, ga, w1, b1, w2, b2):
        tokens = xa.reshape(b * s, h)
        scores = jax.nn.softmax(ga.reshape(b * s, e), axis=-1)
        gates, idx = jax.lax.top_k(scores.T, cap)              # [E, cap]
        picked = jnp.take(tokens, idx.reshape(-1), axis=0).reshape(e, cap, h)
        hmid = jnp.einsum("ech,ehi->eci", picked, w1) + b1
        hmid = jax.nn.gelu(hmid) if act_type == "gelu" else jax.nn.relu(hmid)
        out_e = jnp.einsum("eci,eih->ech", hmid, w2) + b2
        out_e = out_e * gates[..., None]
        flat = jnp.zeros((b * s, h), xa.dtype) \
            .at[idx.reshape(-1)].add(out_e.reshape(e * cap, h))
        return flat.reshape(b, s, h)

    return apply(f, xt, as_tensor(gate), as_tensor(bmm0_weight),
                 as_tensor(bmm0_bias), as_tensor(bmm1_weight),
                 as_tensor(bmm1_bias), name="fused_ec_moe")


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False):
    """Varlen attention over padded batches (reference:
    variable_length_memory_efficient_attention.py:28, cutlass kernel;
    layout [B, H, S, D]). TPU-native: per-row key-validity masking fused
    into one softmax(QK^T)V program — XLA keeps it in registers/VMEM."""
    import jax
    import jax.numpy as jnp

    from ....autograd.function import apply
    from ....core.tensor import as_tensor

    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    d = q.shape[-1]
    sc = float(scale) if scale is not None else d ** -0.5

    def f(qa, ka, va, qlen, kvlen, *maybe_mask):
        hq, hk = qa.shape[1], ka.shape[1]
        if hq != hk:  # GQA: repeat kv heads
            ka2 = jnp.repeat(ka, hq // hk, axis=1)
            va2 = jnp.repeat(va, hq // hk, axis=1)
        else:
            ka2, va2 = ka, va
        s = jnp.einsum("bhqd,bhkd->bhqk", qa * sc, ka2,
                       preferred_element_type=jnp.float32)
        if maybe_mask:
            s = s + maybe_mask[0].astype(jnp.float32)
        kidx = jnp.arange(ka.shape[2])
        valid = kidx[None, None, None, :] < kvlen[:, None, None, None]
        if causal:
            valid = valid & (kidx[None, None, None, :]
                             <= jnp.arange(qa.shape[2])[None, None, :, None])
        s = jnp.where(valid, s, -jnp.inf)
        # a row with zero valid keys would softmax all -inf to NaN and a
        # ragged batch containing one empty sequence would poison every
        # downstream reduction — emit zeros for such rows instead
        any_valid = jnp.any(valid, axis=-1, keepdims=True)
        p = jnp.where(any_valid,
                      jax.nn.softmax(jnp.where(valid, s, -1e30), axis=-1),
                      0.0)
        out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(va2.dtype), va2)
        # query-side padding: rows past seq_lens are zeroed (the reference
        # kernel only writes valid query rows)
        qidx = jnp.arange(qa.shape[2])
        qvalid = qidx[None, None, :, None] < qlen[:, None, None, None]
        return jnp.where(qvalid, out, 0.0)

    args = (q, k, v, as_tensor(seq_lens), as_tensor(kv_seq_lens))
    if mask is not None:
        args = args + (as_tensor(mask),)
    return apply(f, *args, name="variable_length_memory_efficient_attention")


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype='default', out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """One decode step of masked MHA over a static KV cache (reference:
    masked_multihead_attention.py:19; x is this step's fused qkv
    [B, 3*H*D], cache_kv [2, B, H, T, D]). Returns (out, cache_kv') like
    the reference. The int8/quant arguments are GPU-kernel-specific and
    unsupported here (TPU serving quantizes via weight_only_linear)."""
    import jax
    import jax.numpy as jnp

    from ....autograd.function import apply_multi
    from ....core.tensor import as_tensor

    if any(a is not None for a in (qkv_out_scale, out_shift, out_smooth,
                                   beam_cache_offset, cum_offsets)) \
            or out_scale != -1 or rotary_emb_dims:
        raise NotImplementedError(
            "quant/beam/rotary arguments of masked_multihead_attention are "
            "not supported on TPU (use weight_only_linear + F.rope)")
    if cache_kv is None:
        raise ValueError("cache_kv is required")
    xt = as_tensor(x)
    ck = as_tensor(cache_kv)
    _, b, h, t, d = ck.shape

    def f(xa, cka, *rest):
        it = iter(rest)
        ba = next(it) if bias is not None else None
        ma = next(it) if src_mask is not None else None
        sl = next(it) if sequence_lengths is not None else None
        qkv = xa.reshape(b, 3, h, d)
        if ba is not None:
            qkv = qkv + ba.reshape(1, 3, h, d)
        qv, kv, vv = qkv[:, 0], qkv[:, 1], qkv[:, 2]       # [B, H, D]
        pos = (sl.reshape(b) if sl is not None
               else jnp.full((b,), jnp.int32(0)))
        bidx = jnp.arange(b)
        kbuf = cka[0].at[bidx, :, pos].set(kv)
        vbuf = cka[1].at[bidx, :, pos].set(vv)
        s = jnp.einsum("bhd,bhtd->bht", qv * (d ** -0.5), kbuf,
                       preferred_element_type=jnp.float32)
        tidx = jnp.arange(t)
        valid = tidx[None, None, :] <= pos[:, None, None]
        if ma is not None:
            s = s + ma.reshape(b, 1, -1)[:, :, :t].astype(jnp.float32)
        s = jnp.where(valid, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bht,bhtd->bhd", p.astype(vbuf.dtype), vbuf)
        return out.reshape(b, h * d), jnp.stack([kbuf, vbuf])

    args = [xt, ck]
    for t_ in (bias, src_mask, sequence_lengths):
        if t_ is not None:
            args.append(as_tensor(t_))
    return apply_multi(f, *args, name="masked_multihead_attention")


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-05, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-05,
                               training=True, mode='upscale_in_train',
                               ring_id=-1, add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Functional fused MHA block (reference: fused_transformer.py:511):
    [pre-LN ->] qkv proj -> attention(+mask) -> out proj -> dropout ->
    [+residual] [-> post-LN]. qkv_weight is [3, H, hd, D] (or [D, 3*D]
    with transpose_qkv_wb and num_heads)."""
    import jax.numpy as jnp

    from ....nn import functional as F
    from .... import ops
    from ....core.tensor import as_tensor

    if cache_kv is not None or ring_id != -1:
        # silently dropping either would return wrong logits (no cached
        # attention / no tensor-parallel reduce); decode callers use
        # masked_multihead_attention, TP callers the fleet layers
        raise NotImplementedError(
            "fused_multi_head_attention: cache_kv/ring_id are not "
            "supported on TPU (use masked_multihead_attention for decode, "
            "fleet TP layers for tensor parallelism)")
    xt = as_tensor(x)
    dmodel = xt.shape[-1]
    qw = as_tensor(qkv_weight)
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError("num_heads required with transpose_qkv_wb")
        h, hd = num_heads, dmodel // num_heads
    else:
        _, h, hd, _ = qw.shape
    residual = xt
    out = xt
    if pre_layer_norm:
        out = F.layer_norm(out, dmodel, pre_ln_scale, pre_ln_bias,
                           pre_ln_epsilon)
    b, s, _ = out.shape
    if transpose_qkv_wb:
        qkv = ops.matmul(out, qw)                       # [B, S, 3D]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        qkv = ops.reshape(qkv, [b, s, 3, h, hd])
    else:
        qkv = ops.einsum("bsd,thkd->bsthk", out, qw)    # [B, S, 3, H, hd]
        if qkv_bias is not None:
            qkv = qkv + ops.reshape(as_tensor(qkv_bias), [1, 1, 3, h, hd])
    q = ops.reshape(qkv[:, :, 0], [b, s, h, hd])
    k = ops.reshape(qkv[:, :, 1], [b, s, h, hd])
    v = ops.reshape(qkv[:, :, 2], [b, s, h, hd])
    mask = None
    if attn_mask is not None:
        mask = as_tensor(attn_mask)
    attn = F.scaled_dot_product_attention(
        q, k, v, attn_mask=mask,
        dropout_p=attn_dropout_rate if training else 0.0)
    attn = ops.reshape(attn, [b, s, h * hd])
    out = ops.matmul(attn, as_tensor(linear_weight))
    if linear_bias is not None:
        out = out + linear_bias
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = out + residual
    if not pre_layer_norm:
        out = F.layer_norm(out, dmodel, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode='upscale_in_train', ring_id=-1,
                      add_residual=True, name=None):
    """Functional fused FFN block (reference: fused_transformer.py:33):
    [pre-LN ->] linear1 -> act -> dropout1 -> linear2 -> dropout2
    [+residual] [-> post-LN]."""
    from ....nn import functional as F
    from .... import ops
    from ....core.tensor import as_tensor

    xt = as_tensor(x)
    dmodel = xt.shape[-1]
    residual = xt
    out = xt
    if pre_layer_norm:
        out = F.layer_norm(out, dmodel, ln1_scale, ln1_bias, ln1_epsilon)
    out = ops.matmul(out, as_tensor(linear1_weight))
    if linear1_bias is not None:
        out = out + linear1_bias
    out = getattr(F, activation)(out)
    out = F.dropout(out, dropout1_rate, training=training, mode=mode)
    out = ops.matmul(out, as_tensor(linear2_weight))
    if linear2_bias is not None:
        out = out + linear2_bias
    out = F.dropout(out, dropout2_rate, training=training, mode=mode)
    if add_residual:
        out = out + residual
    if pre_layer_norm:
        return out
    return F.layer_norm(out, dmodel, ln2_scale, ln2_bias, ln2_epsilon)


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """AlphaFold-style gated attention (reference:
    fused_gate_attention.py:19; query [B, M, Sq, Dq]). merge_qkv uses one
    [3, H, hd, Dq] projection for self-attention; otherwise separate
    [D, H, hd] q/k/v projections attend query over `key`. The sigmoid gate
    modulates heads before the output projection."""
    import jax
    import jax.numpy as jnp

    from ....autograd.function import apply
    from ....core.tensor import as_tensor

    qt = as_tensor(query)

    def f(qa, *rest):
        it = iter(rest)
        if merge_qkv:
            qkv_w = next(it)
            _, h, hd, _ = qkv_w.shape
            qkv = jnp.einsum("bmsd,thkd->tbmshk", qa, qkv_w)
            qv, kv, vv = qkv[0], qkv[1], qkv[2]       # [B, M, S, H, hd]
        else:
            ka = next(it)
            qw, kw, vw = next(it), next(it), next(it)
            h, hd = qw.shape[-2], qw.shape[-1]
            qv = jnp.einsum("bmsd,dhk->bmshk", qa, qw)
            kv = jnp.einsum("bmsd,dhk->bmshk", ka, kw)
            vv = jnp.einsum("bmsd,dhk->bmshk", ka, vw)
        s = jnp.einsum("bmqhc,bmkhc->bmhqk", qv * (hd ** -0.5), kv,
                       preferred_element_type=jnp.float32)
        if nonbatched_bias is not None:
            s = s + next(it).astype(jnp.float32)
        if attn_mask is not None:
            s = s + next(it).astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bmhqk,bmkhc->bmqhc", p.astype(vv.dtype), vv)
        if has_gating:
            gw = next(it)
            gb = next(it)
            gate = jax.nn.sigmoid(
                jnp.einsum("bmsd,dhc->bmshc", qa, gw) + gb)
            out = out * gate
        ow = next(it)
        out = jnp.einsum("bmshc,hcd->bmsd", out, ow)
        ob = next(it, None)
        return out if ob is None else out + ob

    args = [qt]
    if merge_qkv:
        args.append(as_tensor(qkv_weight))
    else:
        args += [as_tensor(key), as_tensor(query_weight),
                 as_tensor(key_weight), as_tensor(value_weight)]
    if nonbatched_bias is not None:
        args.append(as_tensor(nonbatched_bias))
    if attn_mask is not None:
        args.append(as_tensor(attn_mask))
    if has_gating:
        args += [as_tensor(gate_linear_weight), as_tensor(gate_linear_bias)]
    args.append(as_tensor(out_linear_weight))
    if out_linear_bias is not None:
        args.append(as_tensor(out_linear_bias))
    return apply(f, *args, name="fused_gate_attention")


from .fused_transformer_serving import fused_multi_transformer  # noqa: F401,E402
