"""Fused layer classes (reference: python/paddle/incubate/nn/layer/
fused_transformer.py, fused_linear.py, fused_dropout_add.py — Layer wrappers
over the fused GPU kernels).

TPU design: "fused" here means the layer body is expressed as one traced
composite that XLA fuses into the surrounding matmuls (plus the Pallas flash
kernel for attention) — the layer classes keep the reference's deploy
surface so fused-transformer checkpoints/configs port over."""

from __future__ import annotations

import paddle_tpu as paddle

from ...nn import functional as F
from ...nn.layer import Layer
from . import functional as IF

__all__ = [
    'FusedLinear', 'FusedDropoutAdd', 'FusedBiasDropoutResidualLayerNorm',
    'FusedMultiHeadAttention', 'FusedFeedForward',
    'FusedTransformerEncoderLayer', 'FusedMultiTransformer', 'FusedEcMoe',
]


class FusedLinear(Layer):
    """Reference fused_linear.py FusedLinear (matmul+bias in one kernel;
    XLA fuses the epilogue on TPU)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = ([out_features, in_features] if transpose_weight
                 else [in_features, out_features])
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """Reference fused_dropout_add.py: dropout(x) + y in one pass."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return IF.fused_dropout_add(x, y, p=self.p,
                                    training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """Reference fused_transformer.py FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=paddle.nn.initializer.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=bias_attr,
                                             is_bias=True)

    def forward(self, x, residual):
        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedMultiHeadAttention(Layer):
    """Reference fused_transformer.py FusedMultiHeadAttention: pre/post-LN
    qkv-fused attention + out-proj + residual in one composite (flash kernel
    on the attention core)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"embed_dim ({embed_dim}) must be divisible by num_heads "
                f"({num_heads})")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        # reference checkpoint layout: qkv_weight [3, H, D, E],
        # qkv_bias [3, H, D] — kept verbatim so fused-transformer state
        # dicts load; the einsum below is still ONE MXU contraction
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim],
                                                   attr=linear_weight_attr)
        self.linear_bias = self.create_parameter([embed_dim],
                                                 attr=linear_bias_attr,
                                                 is_bias=True)
        one = paddle.nn.initializer.Constant(1.0)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr, default_initializer=one)
        self.pre_ln_bias = self.create_parameter([embed_dim],
                                                 attr=pre_ln_bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr, default_initializer=one)
        self.ln_bias = self.create_parameter([embed_dim], attr=ln_bias_attr,
                                             is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "FusedMultiHeadAttention is self-attention only (the "
                "reference fused kernel likewise packs qkv from one input); "
                "use nn.MultiHeadAttention for cross-attention")
        if cache is not None:
            raise NotImplementedError("kv-cache decode not supported here")
        x = query
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, self.embed_dim, self.pre_ln_scale,
                             self.pre_ln_bias, self.epsilon)
        b, s, _ = x.shape
        qkv = paddle.einsum("bse,khde->bskhd", x, self.qkv_weight) \
            + self.qkv_bias
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, self.embed_dim, self.ln_scale,
                               self.ln_bias, self.epsilon)
        return out


class FusedFeedForward(Layer):
    """Reference fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate if act_dropout_rate
                                 is not None else dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  attr=linear1_bias_attr,
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter([d_model],
                                                  attr=linear2_bias_attr,
                                                  is_bias=True)
        one = paddle.nn.initializer.Constant(1.0)
        self.ln1_scale = self.create_parameter([d_model], attr=ln1_scale_attr,
                                               default_initializer=one)
        self.ln1_bias = self.create_parameter([d_model], attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter([d_model], attr=ln2_scale_attr,
                                               default_initializer=one)
        self.ln2_bias = self.create_parameter([d_model], attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = F.layer_norm(src, self.d_model, self.ln1_scale,
                               self.ln1_bias, self.epsilon)
        act = getattr(F, self.activation)
        h = act(F.linear(src, self.linear1_weight, self.linear1_bias))
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        h = F.linear(h, self.linear2_weight, self.linear2_bias)
        h = F.dropout(h, self.dropout_rate, training=self.training)
        out = residual + h
        if not self.normalize_before:
            out = F.layer_norm(out, self.d_model, self.ln2_scale,
                               self.ln2_bias, self.epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """Reference fused_transformer.py FusedTransformerEncoderLayer =
    FusedMultiHeadAttention + FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, epsilon=1e-5,
                 name=None):
        super().__init__()
        attn_drop = (attn_dropout_rate if attn_dropout_rate is not None
                     else dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_drop, normalize_before=normalize_before,
            epsilon=epsilon)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before, epsilon=epsilon)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Reference incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer (:1040): per-layer parameter lists driving ONE
    fused serving op (functional.fused_multi_transformer), including the
    [2, B, H, T, D] KV caches and decode `time_step`."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, name=None):
        super().__init__()
        from ...nn.initializer import Constant
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) \
                if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        head_dim = embed_dim // num_heads
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._trans_qkvw = trans_qkvw
        self._dropout_rate = dropout_rate
        self.activation = activation

        def attr_at(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        (self.ln_scales, self.ln_biases, self.qkv_weights, self.qkv_biases,
         self.linear_weights, self.linear_biases, self.ffn_ln_scales,
         self.ffn_ln_biases, self.ffn1_weights, self.ffn1_biases,
         self.ffn2_weights, self.ffn2_biases) = ([] for _ in range(12))
        for i in range(num_layers):
            self.ln_scales.append(self.create_parameter(
                [embed_dim], attr=attr_at(ln_scale_attrs, i),
                default_initializer=Constant(1.0)))
            self.ln_biases.append(self.create_parameter(
                [embed_dim], attr=attr_at(ln_bias_attrs, i), is_bias=True))
            qkv_shape = [3, num_heads, head_dim, embed_dim] if trans_qkvw \
                else [embed_dim, 3, num_heads, head_dim]
            self.qkv_weights.append(self.create_parameter(
                qkv_shape, attr=attr_at(qkv_weight_attrs, i)))
            self.qkv_biases.append(self.create_parameter(
                [3, num_heads, head_dim], attr=attr_at(qkv_bias_attrs, i),
                is_bias=True))
            self.linear_weights.append(self.create_parameter(
                [num_heads * head_dim, embed_dim],
                attr=attr_at(linear_weight_attrs, i)))
            self.linear_biases.append(self.create_parameter(
                [embed_dim], attr=attr_at(linear_bias_attrs, i),
                is_bias=True))
            self.ffn_ln_scales.append(self.create_parameter(
                [embed_dim], attr=attr_at(ffn_ln_scale_attrs, i),
                default_initializer=Constant(1.0)))
            self.ffn_ln_biases.append(self.create_parameter(
                [embed_dim], attr=attr_at(ffn_ln_bias_attrs, i),
                is_bias=True))
            self.ffn1_weights.append(self.create_parameter(
                [embed_dim, dim_feedforward],
                attr=attr_at(ffn1_weight_attrs, i)))
            self.ffn1_biases.append(self.create_parameter(
                [dim_feedforward], attr=attr_at(ffn1_bias_attrs, i),
                is_bias=True))
            self.ffn2_weights.append(self.create_parameter(
                [dim_feedforward, embed_dim],
                attr=attr_at(ffn2_weight_attrs, i)))
            self.ffn2_biases.append(self.create_parameter(
                [embed_dim], attr=attr_at(ffn2_bias_attrs, i), is_bias=True))
        # register the per-layer lists as sublayer parameters
        for lname in ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                      "linear_weights", "linear_biases", "ffn_ln_scales",
                      "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                      "ffn2_weights", "ffn2_biases"):
            for j, p in enumerate(getattr(self, lname)):
                self.add_parameter(f"{lname}_{j}", p)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        from .functional import fused_multi_transformer
        out = fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self._epsilon,
            cache_kvs=caches, pre_caches=pre_caches, seq_lens=seq_lens,
            rotary_embs=rotary_embs, time_step=time_step,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            rotary_emb_dims=rotary_emb_dims, activation=self.activation,
            training=self.training, trans_qkvw=self._trans_qkvw)
        return out


class FusedEcMoe(Layer):
    """Reference fused_ec_moe.py FusedEcMoe: expert-choice MoE ffn — every
    expert picks its top tokens (capacity-balanced by construction), batched
    as one [E, ...] einsum pair on the MXU."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.act_type = act_type
        self.gate = self.create_parameter([hidden_size, num_experts],
                                          attr=weight_attr)
        self.w1 = self.create_parameter([num_experts, hidden_size, inter_size],
                                        attr=weight_attr)
        self.b1 = self.create_parameter([num_experts, 1, inter_size],
                                        attr=bias_attr, is_bias=True)
        self.w2 = self.create_parameter([num_experts, inter_size, hidden_size],
                                        attr=weight_attr)
        self.b2 = self.create_parameter([num_experts, 1, hidden_size],
                                        attr=bias_attr, is_bias=True)

    def forward(self, x):
        import jax.numpy as jnp

        from ...autograd.function import apply

        b, s, h = x.shape
        e = self.num_experts
        cap = max(1, (b * s) // e)
        if self.act_type not in ("gelu", "relu"):
            raise ValueError(f"unsupported act_type {self.act_type!r}")

        def f(xa, gw, w1, b1, w2, b2):
            import jax
            tokens = xa.reshape(b * s, h)
            scores = jax.nn.softmax(tokens @ gw, axis=-1)      # [T, E]
            # expert choice: each expert takes its top-cap tokens
            gates, idx = jax.lax.top_k(scores.T, cap)          # [E, cap]
            picked = jnp.take(tokens, idx.reshape(-1), axis=0) \
                .reshape(e, cap, h)
            hmid = jnp.einsum("ech,ehi->eci", picked, w1) + b1
            hmid = jax.nn.gelu(hmid) if self.act_type == "gelu" \
                else jax.nn.relu(hmid)
            out_e = jnp.einsum("eci,eih->ech", hmid, w2) + b2
            out_e = out_e * gates[..., None]
            flat = jnp.zeros((b * s, h), xa.dtype) \
                .at[idx.reshape(-1)].add(out_e.reshape(e * cap, h))
            return flat.reshape(b, s, h)

        return apply(f, x, self.gate, self.w1, self.b1, self.w2, self.b2,
                     name="fused_ec_moe")
