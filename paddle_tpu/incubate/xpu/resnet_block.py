"""Fused ResNet basic block (reference: python/paddle/incubate/xpu/
resnet_block.py — resnet_basic_block :29, ResNetBasicBlock :327, the
XPU fused kernel resnet_basic_block_op).

The block is conv1-bn1-relu -> conv2-bn2, plus an optional conv3-bn3
shortcut, then add + relu — one traced composition, fused by XLA into a
handful of MXU convs + VPU epilogues (the reference fuses it by hand for
the Kunlun XPU)."""

from __future__ import annotations

import numpy as np

from ...nn import functional as F
from ...nn.layer import Layer

__all__ = ["ResNetBasicBlock", "resnet_basic_block"]


def _bn(x, scale, bias, mean, var, eps, training, momentum, data_format):
    return F.batch_norm(x, mean, var, weight=scale, bias=bias,
                        training=training, momentum=momentum, epsilon=eps,
                        data_format=data_format)


def resnet_basic_block(
        x, filter1, scale1, bias1, mean1, var1, filter2, scale2, bias2,
        mean2, var2, filter3, scale3, bias3, mean3, var3, stride1, stride2,
        stride3, padding1, padding2, padding3, dilation1, dilation2,
        dilation3, groups, momentum, eps, data_format, has_shortcut,
        use_global_stats=None, training=False, trainable_statistics=False,
        find_conv_max=True):
    """Reference resnet_block.py:29 (functional form)."""
    bn_training = training and not use_global_stats
    z = F.conv2d(x, filter1, stride=stride1, padding=padding1,
                 dilation=dilation1, groups=groups, data_format=data_format)
    z = _bn(z, scale1, bias1, mean1, var1, eps, bn_training, momentum,
            data_format)
    z = F.relu(z)
    z = F.conv2d(z, filter2, stride=stride2, padding=padding2,
                 dilation=dilation2, groups=groups, data_format=data_format)
    z = _bn(z, scale2, bias2, mean2, var2, eps, bn_training, momentum,
            data_format)
    if has_shortcut:
        sc = F.conv2d(x, filter3, stride=stride3, padding=padding3,
                      dilation=dilation3, groups=groups,
                      data_format=data_format)
        sc = _bn(sc, scale3, bias3, mean3, var3, eps, bn_training, momentum,
                 data_format)
    else:
        sc = x
    return F.relu(z + sc)


class ResNetBasicBlock(Layer):
    """Reference resnet_block.py:327."""

    def __init__(self, num_channels1, num_filter1, filter1_size,
                 num_channels2, num_filter2, filter2_size, num_channels3,
                 num_filter3, filter3_size, stride1=1, stride2=1, stride3=1,
                 act="relu", momentum=0.9, eps=1e-5, data_format="NCHW",
                 has_shortcut=False, use_global_stats=False,
                 is_test=False, filter1_attr=None, scale1_attr=None,
                 bias1_attr=None, moving_mean1_name=None,
                 moving_var1_name=None, filter2_attr=None, scale2_attr=None,
                 bias2_attr=None, moving_mean2_name=None,
                 moving_var2_name=None, filter3_attr=None, scale3_attr=None,
                 bias3_attr=None, moving_mean3_name=None,
                 moving_var3_name=None, padding1=0, padding2=0, padding3=0,
                 dilation1=1, dilation2=1, dilation3=1,
                 trainable_statistics=False, find_conv_max=True):
        super().__init__()
        if act != "relu":
            raise NotImplementedError(
                "ResNetBasicBlock only supports act='relu' (reference "
                "kernel restriction)")
        self._stride1, self._stride2, self._stride3 = stride1, stride2, \
            stride3
        # reference default: padding = (filter_size - 1) // 2 when 0
        self._padding1 = padding1 or (filter1_size - 1) // 2
        self._padding2 = padding2 or (filter2_size - 1) // 2
        self._padding3 = padding3
        self._dilation1, self._dilation2, self._dilation3 = dilation1, \
            dilation2, dilation3
        self._momentum, self._eps = momentum, eps
        self._data_format = data_format
        self._has_shortcut = has_shortcut
        self._use_global_stats = use_global_stats
        self._is_test = is_test

        def conv_p(co, ci, k, attr):
            std = (2.0 / (k * k * co)) ** 0.5
            from ...nn.initializer import Normal
            return self.create_parameter(
                shape=[co, ci, k, k], attr=attr,
                default_initializer=Normal(0.0, std))

        def bn_p(c, scale_attr, bias_attr):
            from ...nn.initializer import Constant
            scale = self.create_parameter(
                shape=[c], attr=scale_attr,
                default_initializer=Constant(1.0))
            bias = self.create_parameter(shape=[c], attr=bias_attr,
                                         is_bias=True)
            mean = self.create_parameter(
                shape=[c], default_initializer=Constant(0.0))
            mean.stop_gradient = True
            var = self.create_parameter(
                shape=[c], default_initializer=Constant(1.0))
            var.stop_gradient = True
            return scale, bias, mean, var

        self.filter_1 = conv_p(num_filter1, num_channels1, filter1_size,
                               filter1_attr)
        self.scale_1, self.bias_1, self.mean_1, self.var_1 = bn_p(
            num_filter1, scale1_attr, bias1_attr)
        self.filter_2 = conv_p(num_filter2, num_channels2, filter2_size,
                               filter2_attr)
        self.scale_2, self.bias_2, self.mean_2, self.var_2 = bn_p(
            num_filter2, scale2_attr, bias2_attr)
        if has_shortcut:
            self.filter_3 = conv_p(num_filter3, num_channels3, filter3_size,
                                   filter3_attr)
            self.scale_3, self.bias_3, self.mean_3, self.var_3 = bn_p(
                num_filter3, scale3_attr, bias3_attr)
        else:
            self.filter_3 = self.scale_3 = self.bias_3 = None
            self.mean_3 = self.var_3 = None

    def forward(self, x):
        return resnet_basic_block(
            x, self.filter_1, self.scale_1, self.bias_1, self.mean_1,
            self.var_1, self.filter_2, self.scale_2, self.bias_2,
            self.mean_2, self.var_2, self.filter_3, self.scale_3,
            self.bias_3, self.mean_3, self.var_3, self._stride1,
            self._stride2, self._stride3, self._padding1, self._padding2,
            self._padding3, self._dilation1, self._dilation2,
            self._dilation3, 1, self._momentum, self._eps,
            self._data_format, self._has_shortcut,
            use_global_stats=self._use_global_stats,
            training=not self._is_test)
