"""`paddle.incubate.xpu` (reference: python/paddle/incubate/xpu/ — the
fused ResNet basic block). TPU is the alternate accelerator in this
build; the fused block is expressed as one jnp composition that XLA
fuses."""

from . import resnet_block  # noqa: F401
from .resnet_block import ResNetBasicBlock, resnet_basic_block  # noqa: F401

__all__ = ["resnet_block"]
