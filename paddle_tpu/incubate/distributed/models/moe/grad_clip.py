"""MoE-aware global-norm clip (reference: incubate/distributed/models/moe/
grad_clip.py ClipGradForMOEByGlobalNorm).

In the reference, each EP rank holds DIFFERENT experts, so the global norm
must sum expert-grad norms across the moe_group (an allreduce) on top of the
shared-param norms. In this framework all experts live in one stacked
[E, ...] logical array (sharded over the expert axis), so a plain global
norm already sums every expert's grad exactly once — the reference's
cross-rank bookkeeping is subsumed by SPMD. Proof:
tests/test_distributed.py::test_moe_grad_clip_matches_manual_global_norm
checks the applied clip factor equals the hand-computed norm over normal +
expert params together."""

from __future__ import annotations

from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group
