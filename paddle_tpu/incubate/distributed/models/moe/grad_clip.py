"""MoE-aware global-norm clip (reference: incubate/distributed/models/moe/
grad_clip.py ClipGradForMOEByGlobalNorm): expert params' grad norms are
summed once per expert owner. In the SPMD model every grad is logically
global, so the plain global norm is already correct; the class keeps the
reference surface (is_expert_param_func, moe_group)."""

from __future__ import annotations

from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group
