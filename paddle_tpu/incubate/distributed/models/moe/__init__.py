from .moe_layer import MoELayer  # noqa: F401
from .gate import NaiveGate, GShardGate, SwitchGate, BaseGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
