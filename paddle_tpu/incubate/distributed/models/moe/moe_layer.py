"""Expert-parallel MoE layer.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263 —
`MoELayer` routes tokens through `MoEScatter`/`MoEGather` PyLayers (:99,:149)
backed by hand-written `global_scatter`/`global_gather` all-to-all ops.

TPU-native redesign (GShard style): routing is expressed as dispatch/combine
einsums over a [tokens, experts, capacity] one-hot; expert FFNs are stacked
[E, ...] parameters sharded over an expert mesh axis, and the XLA partitioner
lowers the token<->expert einsums into the all-to-all pair over ICI — the
exact comm pattern global_scatter/global_gather implement by hand, but fused
and overlapped by the compiler.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....core.tensor import Tensor, Parameter
from .....autograd.function import apply
from .....autograd.grad_mode import no_grad
from .....nn.layer import Layer
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate

__all__ = ["MoELayer"]


def _functionalize(template: Layer):
    names_params = list(template.named_parameters())
    params = [p for _, p in names_params]

    def expert_fn(param_arrays, x):
        saved = [(p._d, p._node) for p in params]
        for p, a in zip(params, param_arrays):
            p._d = a
            p._node = None
        try:
            with no_grad():
                out = template(Tensor(x))
            return out._d
        finally:
            for p, (d, n) in zip(params, saved):
                p._d = d
                p._node = n

    return [n for n, _ in names_params], params, expert_fn


class MoELayer(Layer):
    """moe_group maps to the expert mesh axis (default 'dp': experts live
    across data-parallel ranks, the reference's usual deployment)."""

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, top_k=2,
                 capacity_factor=1.25, expert_parallel_axis="dp",
                 shared_experts=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.num_expert = len(experts)
        self.capacity_factor = capacity_factor
        self._axis = expert_parallel_axis
        # one shared decision for gate world_size AND stacked-param
        # sharding: the expert axis participates only when it divides the
        # global expert count
        from .....distributed.topology import get_mesh
        mesh = get_mesh()
        self._ep_size = 1
        if mesh is not None and expert_parallel_axis in mesh.axis_names and \
                self.num_expert % mesh.shape[expert_parallel_axis] == 0:
            self._ep_size = mesh.shape[expert_parallel_axis]
        if gate is None or isinstance(gate, dict):
            cfg = gate or {}
            gtype = cfg.get("type", "gshard")
            top_k = cfg.get("top_k", top_k)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            # world_size = expert-axis size: `experts` is the GLOBAL list, so
            # per-rank num_expert * world_size = len(experts) (the reference's
            # tot_expert contract, moe_layer.py:263)
            gate = cls(d_model, self.num_expert // self._ep_size,
                       world_size=self._ep_size, top_k=top_k)
        self.gate = gate
        self.top_k = gate.top_k
        # always-on experts added to every token's output (DeepSeekMoE /
        # Qwen2-MoE shared experts; reference incubate moe shared variants)
        self.shared_experts = shared_experts

        # stack expert params: [E, ...] sharded over the expert axis
        self._param_names, self._template_params, self._expert_fn = \
            _functionalize(experts[0])
        # SwiGLU FFN experts (the Llama/Qwen2-MoE shape) get the grouped-GEMM
        # Pallas path: capacity tiles beyond each expert's fill count are
        # skipped instead of multiplied as zeros (reference: fused MoE
        # grouped-GEMM dispatch kernels)
        self._ffn_fast = self._param_names == [
            "gate_proj.weight", "up_proj.weight", "down_proj.weight"]
        self._stacked: list[Parameter] = []
        for j, pname in enumerate(self._param_names):
            per = [dict(e.named_parameters())[pname]._d for e in experts]
            stacked = Parameter(jnp.stack(per, axis=0),
                                name=f"moe_experts.{pname}")
            from .....distributed.sharding_utils import mark_sharding
            if self._ep_size > 1:
                mark_sharding(stacked,
                              P(self._axis, *([None] * (stacked.ndim - 1))))
            self.add_parameter(f"expert_{j}", stacked)
            self._stacked.append(stacked)
        self.l_aux = None

    def forward(self, x):
        b_shape = x.shape
        h = self.d_model
        tokens = x.reshape([-1, h])
        n = tokens.shape[0]
        e = self.num_expert
        k = self.top_k
        capacity = max(int(math.ceil(self.capacity_factor * n * k / e)), 1)

        logits = self.gate(tokens)  # [n, e]
        expert_fn = self._expert_fn
        n_params = len(self._stacked)
        from .....core.flags import flag
        from .....ops.kernels import _common as kern
        use_grouped = (self._ffn_fast and kern.available()
                       and flag("use_pallas_kernels"))
        interpret = kern.interpret_mode()

        def jfn(tok, lg, *stacked):
            probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
            # top-k routing
            topv, topi = jax.lax.top_k(probs, k)          # [n, k]
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
            route_oh = jax.nn.one_hot(topi, e, dtype=jnp.float32)  # [n, k, e]
            # position of each token within its expert queue
            pos = jnp.cumsum(route_oh.reshape(-1, e), axis=0).reshape(n, k, e) \
                - route_oh  # 0-based arrival order
            keep = pos < capacity
            onehot = route_oh * keep                      # post-capacity-drop
            pos_idx = jnp.einsum("nke->nk", pos * onehot).astype(jnp.int32)
            cap_oh = jax.nn.one_hot(jnp.where(jnp.sum(onehot, -1) > 0,
                                              pos_idx, capacity),
                                    capacity + 1, dtype=jnp.float32)[..., :capacity]
            # dispatch [n, e, c] / combine [n, e, c]
            dispatch = jnp.einsum("nke,nkc->nec", onehot, cap_oh)
            combine = jnp.einsum("nk,nke,nkc->nec", topv, onehot, cap_oh)
            expert_in = jnp.einsum("nec,nh->ech", dispatch,
                                   tok.astype(jnp.float32)).astype(tok.dtype)
            stacked_params = list(stacked)

            if use_grouped:
                from .....ops.kernels.moe_gemm_pallas import grouped_matmul
                counts = jnp.sum(dispatch, axis=(0, 2)).astype(jnp.int32)
                gate_w, up_w, down_w = stacked_params
                gh = grouped_matmul(expert_in, gate_w, counts, interpret)
                uh = grouped_matmul(expert_in, up_w, counts, interpret)
                act = (jax.nn.silu(gh.astype(jnp.float32))
                       * uh.astype(jnp.float32)).astype(expert_in.dtype)
                expert_out = grouped_matmul(act, down_w, counts, interpret)
            else:
                def run_one(param_arrays, xin):
                    return expert_fn(param_arrays, xin)
                expert_out = jax.vmap(run_one)(stacked_params, expert_in)
            out = jnp.einsum("nec,ech->nh", combine,
                             expert_out.astype(jnp.float32)).astype(tok.dtype)
            # aux load-balance loss (GShard eq.(4), generalised to top-k):
            # f_i = fraction of routing slots assigned to expert i BEFORE the
            # capacity drop (load balance must see intended routing, not the
            # post-drop truncation), m_i = mean gate prob; aux = E * f . m
            me = jnp.mean(probs, axis=0)
            ce = jnp.mean(jnp.sum(route_oh, axis=1) / k, axis=0)
            aux = jnp.sum(me * ce) * e
            return out, aux

        out, aux = _apply2(jfn, tokens, logits, self._stacked)
        self.l_aux = aux
        out = out.reshape(b_shape)
        if self.shared_experts is not None:
            out = out + self.shared_experts(x)
        return out


def _apply2(jfn, tokens, logits, stacked):
    from .....autograd.function import apply_multi
    out, aux = apply_multi(lambda *arrs: jfn(arrs[0], arrs[1], *arrs[2:]),
                           tokens, logits, *stacked, name="moe")
    return out, aux
