"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py).

Gates produce per-token expert scores; routing/capacity logic lives in
MoELayer (GShard-style dispatch/combine einsums so XLA can lay the all-to-all
over the expert mesh axis).
"""

from __future__ import annotations

import jax.numpy as jnp

from .....nn.layer import Layer
from .....nn.layers.common import Linear
from .....nn import functional as F

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


class BaseGate(Layer):
    def __init__(self, d_model, num_expert, world_size=1, top_k=2):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.world_size = world_size
        self.tot_expert = num_expert * world_size
        self.top_k = top_k
        self.loss = None

    def forward(self, x):
        raise NotImplementedError


class NaiveGate(BaseGate):
    """Plain linear top-k gate (reference naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2):
        super().__init__(d_model, num_expert, world_size, top_k)
        self.gate = Linear(d_model, self.tot_expert)

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """GShard gate: top-2 with aux load-balance loss (reference
    gshard_gate.py). The aux loss (mean_prob * fraction_routed * E) is
    computed in MoELayer where routing fractions are known."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        super().__init__(d_model, num_expert, world_size, top_k)
        self.capacity = capacity


class SwitchGate(NaiveGate):
    """Switch-Transformer top-1 gate (reference switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, top_k=1)
        self.switch_eps = switch_eps
