"""`paddle.incubate.distributed.fleet` (reference:
python/paddle/incubate/distributed/fleet/__init__.py — recompute
re-exports)."""

from ....distributed.fleet.recompute import (  # noqa: F401
    recompute_hybrid, recompute_sequential)
from . import fleet_util  # noqa: F401
from . import utils  # noqa: F401
from .fleet_util import FleetUtil, GPUPSUtil  # noqa: F401

__all__ = ["recompute_hybrid", "recompute_sequential"]
