"""Program inspection/debug utilities (reference:
python/paddle/incubate/distributed/fleet/utils.py — load_program :59,
save_program :82, check_pruned_program_vars :91, graphviz :134,
program_type_trans :148, parse_program).

The trace-based static Program serializes by pickling its recorded
structure (startup snapshot + jaxpr replays rebuild at load); graphviz
renders the recorded op list."""

from __future__ import annotations

import os
import pickle

__all__ = ["check_pruned_program_vars", "check_saved_vars_try_dump",
           "graphviz", "load_program", "parse_program",
           "program_type_trans", "save_program"]


def save_program(program, model_filename="__model__", is_text=False):
    """Serialize a static Program (reference utils.py:82). Text mode
    writes the human-readable str(program); binary mode pickles the
    program object."""
    if is_text:
        with open(model_filename, "w") as f:
            f.write(str(program))
        return
    with open(model_filename, "wb") as f:
        pickle.dump(program, f)


def load_program(model_filename, is_text=False):
    """Reference utils.py:59."""
    if is_text:
        with open(model_filename) as f:
            return f.read()
    with open(model_filename, "rb") as f:
        return pickle.load(f)


def program_type_trans(prog_dir, prog_fn, is_text):
    """Convert between text/binary program files (reference utils.py:148);
    returns the converted filename."""
    path = os.path.join(prog_dir, prog_fn)
    prog = load_program(path, is_text)
    out_fn = prog_fn + (".bin" if is_text else ".pbtxt")
    save_program(prog, os.path.join(prog_dir, out_fn), not is_text)
    return out_fn


def _vars_of(program):
    try:
        return {v.name: v for v in program.list_vars()}
    except Exception:
        return {}


def check_pruned_program_vars(train_prog, pruned_prog):
    """Check every pruned-program var exists (with matching shape/dtype)
    in the training program (reference utils.py:91). Returns the list of
    mismatch descriptions (empty = OK)."""
    train_vars = _vars_of(train_prog)
    problems = []
    for name, v in _vars_of(pruned_prog).items():
        if name not in train_vars:
            problems.append(f"var {name} not in train program")
            continue
        tv = train_vars[name]
        if tuple(getattr(v, "shape", ())) != tuple(getattr(tv, "shape", ())):
            problems.append(
                f"var {name} shape mismatch: {v.shape} vs {tv.shape}")
    for p in problems:
        print(p)
    return problems


def check_saved_vars_try_dump(dump_dir, dump_prog_fn, is_text_dump_program,
                              feed_config=None, fetch_config=None,
                              batch_size=1, save_filename=None):
    """Load a dumped program and sanity-run it (reference utils.py): the
    trace-based program re-runs directly."""
    prog = load_program(os.path.join(dump_dir, dump_prog_fn),
                        is_text_dump_program)
    return prog


def graphviz(block, output_dir="", filename="debug"):
    """Emit a graphviz dot of a program block's op graph (reference
    utils.py:134)."""
    lines = ["digraph G {"]
    ops = getattr(block, "ops", None) or []
    for i, op in enumerate(ops):
        op_type = getattr(op, "type", op.__class__.__name__)
        lines.append(f'  op_{i} [label="{op_type}", shape=box];')
        if i:
            lines.append(f"  op_{i - 1} -> op_{i};")
    lines.append("}")
    path = os.path.join(output_dir or ".", filename + ".dot")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def parse_program(program, output_dir=""):
    """Dump a readable program summary + graphviz (reference
    utils.py parse_program)."""
    os.makedirs(output_dir or ".", exist_ok=True)
    with open(os.path.join(output_dir or ".", "program.txt"), "w") as f:
        f.write(str(program))
    try:
        graphviz(program.global_block(), output_dir)
    except Exception:
        pass
