"""Fleet production utilities (reference:
python/paddle/incubate/distributed/fleet/fleet_util.py — FleetUtil :42,
~1500 LoC of pslib day/pass model management; GPUPSUtil in
incubate/distributed/fleet/fs.py analog).

Scope note (COVERAGE honest): the day/pass donefile choreography is
HDFS-centric production tooling; this build implements the metric,
rank-gated logging, and model save/load core over the TPU-native
checkpoint path and LocalFS/HDFSClient, keeping the method surface."""

from __future__ import annotations

import os
import time

__all__ = ["FleetUtil", "GPUPSUtil"]


class FleetUtil:
    """Reference fleet_util.py:42."""

    def __init__(self, mode="pslib"):
        self.mode = mode

    # -- rank-gated logging (reference :75/:96/:116)
    def _rank0(self):
        from ....distributed.fleet import fleet
        try:
            return fleet.worker_index() == 0
        except Exception:
            return True

    def rank0_print(self, s):
        if self._rank0():
            print(s)

    def rank0_info(self, s):
        if self._rank0():
            from ....distributed.fleet.utils.log_util import logger
            logger.info(s)

    def rank0_error(self, s):
        if self._rank0():
            from ....distributed.fleet.utils.log_util import logger
            logger.error(s)

    # -- metrics (reference :136/:166/:211)
    def set_zero(self, var_name, scope=None, place=None, param_type="int64"):
        """Zero a metric accumulator var in the live scope."""
        from .... import static
        import numpy as np
        import jax.numpy as jnp
        scope = scope or static.global_scope()
        var = scope.find_var(var_name)
        if var is not None:
            t = var.get_tensor()
            t.set(np.zeros(t.shape(), param_type), place)

    def get_global_auc(self, scope=None, stat_pos="_generated_var_2",
                       stat_neg="_generated_var_3"):
        """Global AUC from pos/neg stat arrays all-reduced across workers
        (reference :211)."""
        from .... import static
        import numpy as np
        scope = scope or static.global_scope()
        pos_var = scope.find_var(stat_pos)
        neg_var = scope.find_var(stat_neg)
        if pos_var is None or neg_var is None:
            return None
        pos = np.array(pos_var.get_tensor()).ravel()
        neg = np.array(neg_var.get_tensor()).ravel()
        try:
            from ....distributed import communication as comm
            gathered_p, gathered_n = [], []
            comm.all_gather_object(gathered_p, pos)
            comm.all_gather_object(gathered_n, neg)
            pos = sum(gathered_p)
            neg = sum(gathered_n)
        except Exception:
            pass
        # AUC over threshold buckets (reference formula)
        total_pos = pos.sum()
        total_neg = neg.sum()
        if total_pos == 0 or total_neg == 0:
            return 0.5
        area = 0.0
        cum_pos = cum_neg = 0.0
        for p, n_ in zip(pos[::-1], neg[::-1]):
            area += n_ * (cum_pos + p / 2.0)
            cum_pos += p
            cum_neg += n_
        return float(area / (total_pos * total_neg))

    def print_global_auc(self, scope=None, stat_pos="_generated_var_2",
                         stat_neg="_generated_var_3",
                         print_prefix=""):
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f"{print_prefix} global auc = {auc}")

    # -- model management over the TPU-native checkpoint path
    def save_fleet_model(self, path, mode=0):
        """Reference :333 — rank-0 saves the live program state."""
        from .... import static
        if self._rank0():
            prog = static.default_main_program()
            from ....incubate.distributed.fleet.utils import save_program
            os.makedirs(path, exist_ok=True)
            save_program(prog, os.path.join(path, "__model__"))

    def load_fleet_model(self, path, mode=0):
        from ....incubate.distributed.fleet.utils import load_program
        return load_program(os.path.join(path, "__model__"))

    def load_fleet_model_one_table(self, table_id, path):
        return self.load_fleet_model(path)

    def save_paddle_inference_model(self, executor, scope, program,
                                    feeded_vars, target_vars, output_path,
                                    day, pass_id, hadoop_fs_name=None,
                                    hadoop_fs_ugi=None, **kwargs):
        """Reference :940 — day/pass-structured inference export over
        static.save_inference_model."""
        from .... import static
        dest = os.path.join(output_path, str(day), str(pass_id),
                            "inference_model")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        static.save_inference_model(dest, feeded_vars, target_vars,
                                    executor, program=program)
        return dest

    def save_paddle_params(self, executor, scope, program, model_name,
                           output_path, day, pass_id, **kwargs):
        """Reference :1032."""
        import paddle_tpu as paddle
        dest = os.path.join(output_path, str(day), str(pass_id), model_name)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        state = {name: var for name, var in
                 ((v.name, v) for v in program.list_vars())}
        paddle.save(state, dest)
        return dest

    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass, is_data_hourly_placed):
        """Reference :1290 — enumerate pass windows inside a day."""
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left_train_hour = int(hours.split(" ")[0]) if isinstance(hours, str) \
            else int(hours[0])
        online_pass_interval = []
        for i in range(pass_per_day):
            passes = []
            for j in range(split_per_pass):
                split_idx = i * split_per_pass + j
                h = split_idx * split_interval // 60
                m = split_idx * split_interval % 60
                if is_data_hourly_placed:
                    passes.append(f"{h:02d}")
                else:
                    passes.append(f"{h:02d}{m:02d}")
            online_pass_interval.append(passes)
        _ = left_train_hour
        return online_pass_interval

    def write_model_donefile(self, output_path, day, pass_id, xbox_base_key,
                             hadoop_fs_name=None, hadoop_fs_ugi=None,
                             monitor_data={}, **kwargs):
        """Reference :397 — records a done marker for (day, pass)."""
        if not self._rank0():
            return
        donefile = os.path.join(output_path, "donefile.txt")
        os.makedirs(output_path, exist_ok=True)
        with open(donefile, "a") as f:
            f.write(f"{day}\t{pass_id}\t{xbox_base_key}\t{time.time()}\n")
        return donefile

    def get_last_save_model(self, output_path, hadoop_fs_name=None,
                            hadoop_fs_ugi=None, **kwargs):
        """Reference :1236 — last (day, pass) recorded in the donefile."""
        donefile = os.path.join(output_path, "donefile.txt")
        if not os.path.exists(donefile):
            return [-1, -1, None, -1]
        with open(donefile) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        if not lines:
            return [-1, -1, None, -1]
        day, pass_id, key, ts = lines[-1].split("\t")
        return [int(day), int(pass_id), key, float(ts)]


class GPUPSUtil(FleetUtil):
    """Reference incubate/distributed/fleet/fleet_util GPUPSUtil: the
    AFS/HDFS-backed variant; file ops ride the fs clients."""

    def __init__(self, fs_client=None):
        super().__init__(mode="pslib")
        if fs_client is None:
            from ....distributed.fleet.utils.fs import LocalFS
            fs_client = LocalFS()
        self._afs = fs_client

    def set_fsclient(self, fs_client):
        self._afs = fs_client
