"""Gather-and-save for hybrid-parallel state (reference:
python/paddle/incubate/distributed/utils/io/dist_save.py — save :31).

`gather_to` collects every rank's shard of a dp/sharding-parallel state
dict onto the destination rank(s), which then saves one unified file via
paddle.save. On the TPU build sharded arrays are jax global arrays whose
replication is handled by the checkpoint layer, so gathering is
materializing the full value host-side."""

from __future__ import annotations

__all__ = ["save", "save_for_auto_inference"]


def _gather_value(v):
    import numpy as np
    num = getattr(v, "numpy", None)
    return np.asarray(num()) if num else v


def save(state_dict, path, **configs):
    """Reference dist_save.py:31. configs: gather_to (int|list, default 0),
    state_type ('params'|'opt'), max_grouped_size."""
    gather_to = configs.pop("gather_to", 0)
    configs.pop("state_type", None)
    configs.pop("max_grouped_size", None)
    import paddle_tpu as paddle
    from .....distributed.fleet import fleet
    rank = fleet.worker_index()
    dests = gather_to if isinstance(gather_to, (list, tuple)) else [gather_to]
    gathered = {k: _gather_value(v) for k, v in state_dict.items()} \
        if isinstance(state_dict, dict) else state_dict
    if rank in dests or fleet._role_maker is None:
        paddle.save(gathered, path, **configs)


def save_for_auto_inference(path_prefix, dist_model, cvt2cpu=False):
    from .save_for_auto import save_for_auto_inference as _impl
    return _impl(path_prefix, dist_model, cvt2cpu)
