"""Export a hybrid-parallel model for auto-parallel inference (reference:
python/paddle/incubate/distributed/utils/io/save_for_auto.py
save_for_auto_inference): writes <prefix>_dist<rank>.pdparams plus the
dist attr mapping so the auto-parallel loader can reshard."""

from __future__ import annotations

import os
import pickle

__all__ = ["save_for_auto_inference"]


def save_for_auto_inference(path_prefix, dist_model, cvt2cpu=False):
    import numpy as np
    import paddle_tpu as paddle
    from .....distributed.fleet import fleet
    rank = fleet.worker_index()
    state = dist_model.state_dict() if hasattr(dist_model, "state_dict") \
        else dict(dist_model)
    params = {k: np.asarray(v.numpy()) if hasattr(v, "numpy") else v
              for k, v in state.items()}
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    paddle.save(params, f"{path_prefix}_dist{rank}.pdparams")
    # dist attrs: sharding spec per param (None for replicated)
    attrs = {}
    for k, v in state.items():
        spec = getattr(v, "_sharding_spec", None)
        attrs[k] = {"dims_mapping": spec} if spec is not None else {}
    with open(f"{path_prefix}_dist{rank}.pdattr", "wb") as f:
        pickle.dump(attrs, f)
    return path_prefix
