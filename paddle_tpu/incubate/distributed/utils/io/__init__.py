"""Distributed save/load helpers (reference:
python/paddle/incubate/distributed/utils/io/)."""

from . import dist_save  # noqa: F401
from . import save_for_auto  # noqa: F401
from .dist_load import load  # noqa: F401
from .dist_save import save  # noqa: F401
from .save_for_auto import save_for_auto_inference  # noqa: F401

__all__ = ["save", "load", "save_for_auto_inference"]
