"""Distributed-aware load (reference:
python/paddle/incubate/distributed/utils/io/dist_load.py load): loads a
unified file on every rank; sharded parameters pick their shard at
assignment time via the sharding spec."""

from __future__ import annotations

__all__ = ["load"]


def load(path, **configs):
    import paddle_tpu as paddle
    place = configs.pop("place", None)
    _ = place  # device placement is the runtime's job on TPU
    return paddle.load(path, **configs)
