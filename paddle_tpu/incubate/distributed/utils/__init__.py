"""`paddle.incubate.distributed.utils` (reference:
python/paddle/incubate/distributed/utils/)."""

from . import io  # noqa: F401
