from . import distributed  # noqa: F401
from . import autotune  # noqa: F401
from . import xpu  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import checkpoint  # noqa: F401
from . import optimizer  # noqa: F401
from . import operators  # noqa: F401
from .operators import (  # noqa: F401
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)

# -- legacy incubate surface: aliases over geometric/ + the wrapper
# optimizers (reference: python/paddle/incubate/__init__.py __all__) --------
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..geometric import (  # noqa: F401
    segment_sum, segment_mean, segment_max, segment_min)


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Legacy alias of geometric.send_u_recv (reference:
    incubate/operators/graph_send_recv.py)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Legacy alias of geometric.sample_neighbors (reference:
    incubate/operators/graph_sample_neighbors.py)."""
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids,
                            perm_buffer=perm_buffer)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Legacy alias of geometric.reindex_graph (reference:
    incubate/operators/graph_reindex.py)."""
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighborhood sampling (reference:
    incubate/operators/graph_khop_sampler.py:21): repeated
    sample_neighbors, then one compact renumbering over the union of
    frontiers. Returns (edge_src, edge_dst, sample_index,
    reindex_nodes[, edge_eids]) — sample_index holds the ORIGINAL ids of
    every involved node (input-first order), reindex_nodes the compact
    positions of input_nodes. Host-side numpy like the sampling readers
    (this path never traces)."""
    import numpy as np

    from ..core.tensor import Tensor, as_tensor
    from ..geometric import sample_neighbors

    cur = input_nodes
    frontiers_np = [as_tensor(input_nodes).numpy()]
    all_neigh, all_cnt, all_eids = [], [], []
    for size in sample_sizes:
        if return_eids:
            neigh, cnt, eids = sample_neighbors(
                row, colptr, cur, sample_size=size, eids=sorted_eids,
                return_eids=True)
            all_eids.append(eids.numpy())
        else:
            neigh, cnt = sample_neighbors(row, colptr, cur,
                                          sample_size=size)
        all_neigh.append(neigh.numpy())
        all_cnt.append(cnt.numpy())
        cur = neigh                       # next frontier: this hop's output
        frontiers_np.append(neigh.numpy())

    # compact id space: input nodes first, then first-seen sampled nodes
    flat = np.concatenate(frontiers_np)
    uniq, first_idx = np.unique(flat, return_index=True)
    uniq = uniq[np.argsort(first_idx)]
    remap = {int(v): i for i, v in enumerate(uniq)}
    # dst of each edge is the frontier NODE it was sampled for — remap the
    # node id itself, never its (possibly duplicated) frontier position
    centers = np.concatenate(frontiers_np[:-1])
    counts = np.concatenate(all_cnt)
    dst_nodes = np.repeat(centers, counts)
    dst = np.asarray([remap[int(v)] for v in dst_nodes], np.int64)
    src = np.asarray([remap[int(v)] for v in np.concatenate(all_neigh)],
                     np.int64)
    reindex_nodes = np.asarray(
        [remap[int(v)] for v in frontiers_np[0]], np.int64)
    out = (Tensor(src), Tensor(dst), Tensor(uniq.astype(np.int64)),
           Tensor(reindex_nodes))
    if return_eids:
        return out + (Tensor(np.concatenate(all_eids)),)
    return out


def identity_loss(x, reduction="none"):
    """Reduction marker for the final loss (reference:
    incubate/nn/loss.py:21; int codes 0=sum, 1=mean, 2=none)."""
    from ..core.tensor import as_tensor

    xt = as_tensor(x)
    if reduction in ("sum", 0):
        return xt.sum()
    if reduction in ("mean", 1):
        return xt.mean()
    if reduction in ("none", 2):
        return xt
    raise ValueError(f"unknown reduction {reduction!r}")
