"""Epoch-level automatic train resumption (reference:
python/paddle/base/incubate/checkpoint/auto_checkpoint.py —
`train_epoch_range` generator that checkpoints per-epoch progress to a
filesystem and fast-forwards past completed epochs on restart).

TPU build: the same contract over the fleet fs abstraction. Usage:

    for epoch in train_epoch_range(10, save_checkpoint_inter=0):
        train_one_epoch()
        # attach model/optimizer state with epoch_range.save(...)

On relaunch with the same PADDLE_JOB_ID the range resumes after the last
completed epoch, restoring any attached state."""

from __future__ import annotations

import json
import os
import time

__all__ = ['train_epoch_range', 'TrainEpochRange', 'get_checkpoint_path',
           'current_epoch_range']

_CURRENT = None


def current_epoch_range():
    """The TrainEpochRange currently iterating (reference
    g_train_epoch_range accessor), or None outside a loop."""
    return _CURRENT


def get_checkpoint_path(name=None):
    root = os.environ.get(
        'PADDLE_TPU_CHECKPOINT_DIR',
        os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu',
                     'auto_checkpoint'))
    job = name or os.environ.get('PADDLE_JOB_ID', 'default_job')
    return os.path.join(root, job)


class TrainEpochRange:
    """Iterable over epochs that persists progress (reference
    TrainEpochRange: _serial_load/save around an epoch loop)."""

    def __init__(self, max_epoch_num, name=None, save_checkpoint_inter=None):
        self._max = int(max_epoch_num)
        self._name = name
        self._dir = get_checkpoint_path(name)
        self._meta_path = os.path.join(self._dir, 'range_meta.json')
        self._inter = save_checkpoint_inter  # seconds between saves; 0=every
        self._last_save = 0.0
        self._restored_epoch = -1
        self._state_objs = {}
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                meta = json.load(f)
            if meta.get('max_epoch_num') == self._max:
                self._restored_epoch = int(meta.get('epoch', -1))

    # -- attachable state -------------------------------------------------
    def attach(self, **named):
        """Attach objects with state_dict/set_state_dict (layers,
        optimizers); their state rides each epoch checkpoint."""
        self._state_objs.update(named)
        if self._restored_epoch >= 0:
            self._restore_states()
        return self

    def _state_file(self):
        return os.path.join(self._dir, 'states.pdparams')

    def _restore_states(self):
        path = self._state_file()
        if not os.path.exists(path) or not self._state_objs:
            return
        from ...framework.io import load
        blob = load(path)
        for k, obj in self._state_objs.items():
            if k in blob and hasattr(obj, 'set_state_dict'):
                obj.set_state_dict(blob[k])

    def _save(self, epoch, force=False):
        now = time.monotonic()
        if not force and self._inter and (now - self._last_save) < self._inter:
            return
        self._last_save = now
        os.makedirs(self._dir, exist_ok=True)
        if self._state_objs:
            from ...framework.io import save
            # write-then-rename: a crash mid-pickle must not corrupt the
            # checkpoint the resume depends on
            stmp = self._state_file() + '.tmp'
            save({k: obj.state_dict()
                  for k, obj in self._state_objs.items()}, stmp)
            os.replace(stmp, self._state_file())
        tmp = self._meta_path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'epoch': epoch, 'max_epoch_num': self._max,
                       'ts': time.time()}, f)
        os.replace(tmp, self._meta_path)  # atomic commit marker

    @property
    def restored_from(self):
        return self._restored_epoch

    def __iter__(self):
        global _CURRENT
        _CURRENT = self
        try:
            for e in range(self._restored_epoch + 1, self._max):
                yield e
                # the final epoch always commits: interval throttling must
                # not leave a cleanly-finished job looking unfinished. A
                # crash or break mid-epoch deliberately does NOT flush —
                # the live state is mid-epoch and must not be recorded as
                # a completed one.
                self._save(e, force=(e == self._max - 1))
        finally:
            _CURRENT = None

    def clean(self):
        import shutil
        if os.path.isdir(self._dir):
            shutil.rmtree(self._dir)


def train_epoch_range(max_epoch_num, name=None, save_checkpoint_inter=None):
    return TrainEpochRange(max_epoch_num, name=name,
                           save_checkpoint_inter=save_checkpoint_inter)
