"""Auto-tuning configuration (reference: python/paddle/incubate/
autotune.py set_config :24).

Maps the reference's three tuning domains onto the TPU build:
- kernel: toggles the measured Pallas row-block autotuner
  (ops/kernels/_common.py block overrides) within a tuning-iteration
  window;
- layout: XLA already picks layouts on TPU — the switch is recorded and
  surfaced via get_config for parity;
- dataloader: records the num_workers tuning request consumed by
  io.DataLoader when auto_tune=True.
"""

from __future__ import annotations

import json

__all__ = ["set_config"]

_CONFIG = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}


def set_config(config=None):
    """Reference autotune.py:24: accepts a dict or a json file path; None
    enables every domain."""
    if config is None:
        for dom in _CONFIG.values():
            dom["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError("config should be a dict or a json file path")
    for key in ("kernel", "layout", "dataloader"):
        if key not in config:
            continue
        dom = config[key]
        if not isinstance(dom, dict):
            raise TypeError(f"config[{key!r}] should be a dict")
        if "enable" in dom:
            if not isinstance(dom["enable"], bool):
                raise TypeError(f"{key}.enable should be bool")
            _CONFIG[key]["enable"] = dom["enable"]
        if key == "kernel" and "tuning_range" in dom:
            rng = list(dom["tuning_range"])
            if len(rng) != 2:
                raise ValueError("kernel.tuning_range should be [start, end]")
            _CONFIG[key]["tuning_range"] = rng
        if key == "dataloader" and "num_workers" in dom:
            _CONFIG[key]["num_workers"] = int(dom["num_workers"])


def get_config():
    """Current tuning configuration (consumed by the kernel autotuner and
    DataLoader)."""
    return {k: dict(v) for k, v in _CONFIG.items()}
