"""Fused incubate operators (reference: python/paddle/incubate/operators/).

`softmax_mask_fuse` / `softmax_mask_fuse_upper_triangle` back the non-flash
attention-score path (reference softmax_mask_fuse.py:20,
softmax_mask_fuse_upper_triangle.py:20 over the fused_softmax_mask CUDA
kernels). On TPU both dispatch to one Pallas VMEM pass per row block
(ops/kernels/softmax_mask_pallas.py); the causal variant never materializes
the [sq, sk] triangle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.function import apply
from ..core.tensor import Tensor, as_tensor


def _use_kernel(x, mask=None):
    from ..core.flags import flag
    from ..ops.kernels import _common as kern
    if not (kern.available() and flag("use_pallas_kernels") and x.ndim == 4):
        return False
    if mask is None:
        return True
    # kernel contract: mask broadcastable to [B, 1, Sq, Sk] (head axis is
    # folded in the index map); anything else takes the composite so the
    # same call never works on one backend and crashes on another
    if mask.ndim != 4 or mask.shape[1] != 1:
        return False
    want = (x.shape[0], 1) + tuple(x.shape[2:])
    return all(ms in (1, xs) for ms, xs in zip(tuple(mask.shape), want))


def softmax_mask_fuse(x, mask, name=None) -> Tensor:
    """out = softmax(x + mask) over the last axis; x [B, H, Sq, Sk], mask
    broadcastable [B, 1, Sq, Sk] (reference contract)."""
    xt = as_tensor(x)
    mt = as_tensor(mask)
    if _use_kernel(xt, mt):
        from ..ops.kernels import _common as kern
        from ..ops.kernels import softmax_mask_pallas as sm
        return apply(
            lambda a, m: sm.softmax_mask_fused(a, m, kern.interpret_mode()),
            xt, mt, name="softmax_mask_fuse")
    return apply(
        lambda a, m: jax.nn.softmax(a.astype(jnp.float32)
                                    + m.astype(jnp.float32),
                                    axis=-1).astype(a.dtype),
        xt, mt, name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x) -> Tensor:
    """Causal masked softmax: entries above the diagonal are masked out
    before the row softmax; the triangle is generated in-kernel."""
    xt = as_tensor(x)
    if _use_kernel(xt):
        from ..ops.kernels import _common as kern
        from ..ops.kernels import softmax_mask_pallas as sm
        return apply(
            lambda a: sm.softmax_mask_tri(a, kern.interpret_mode()),
            xt, name="softmax_mask_fuse_upper_triangle")

    def f(a):
        sq, sk = a.shape[-2:]
        keep = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
        af = jnp.where(keep, a.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(af, axis=-1).astype(a.dtype)

    return apply(f, xt, name="softmax_mask_fuse_upper_triangle")
