"""paddle.sparse equivalent (reference: python/paddle/sparse/ —
creation.py sparse_coo_tensor/sparse_csr_tensor, unary/binary ops, nn).

TPU design: sparse values ride jax.experimental.sparse.BCOO — XLA lowers
sparse-dense matmuls to gather/scatter programs, which is the honest TPU
story (no sparse tensor cores). The SparseTensor wrapper keeps the
reference surface: indices()/values()/to_dense()/nnz, add/mul, matmul,
relu, and coalesce.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, as_tensor

__all__ = ["SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
           "to_dense", "add", "multiply", "matmul", "relu", "coalesce",
           "is_sparse", "abs", "sin", "tan", "asin", "atan", "sinh", "tanh",
           "asinh", "atanh", "acos", "acosh", "sqrt", "square", "log1p",
           "expm1", "neg", "relu6", "leaky_relu", "isnan", "pow", "scale",
           "cast", "subtract", "divide", "divide_scalar", "sum", "reshape",
           "transpose", "slice", "full_like", "addmm", "mv", "masked_matmul",
           "softmax", "to_sparse_coo", "to_sparse_csr", "deg2rad",
           "rad2deg", "is_same_shape", "pca_lowrank"]


class SparseCooTensor:
    """COO sparse tensor over BCOO (reference core SparseCooTensor)."""

    def __init__(self, bcoo: "jsparse.BCOO"):
        self._b = bcoo

    # -- reference accessors -------------------------------------------------
    @property
    def shape(self):
        return list(self._b.shape)

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._b.indices, 0, 1),
                      stop_gradient=True)  # [ndim, nnz] reference layout

    def values(self) -> Tensor:
        return Tensor(self._b.data, stop_gradient=True)

    @property
    def nnz(self) -> int:
        return int(self._b.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._b.todense(), stop_gradient=True)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._b.sum_duplicates())

    def is_sparse(self):
        return True

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """indices: [ndim, nnz] (reference layout); values: [nnz]."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor)
                     else indices)
    val = as_tensor(values)._data
    if dtype is not None:
        from ..core.dtype import dtype_from_any
        val = val.astype(dtype_from_any(dtype).np_dtype)
    if shape is None:
        shape = tuple(int(i.max()) + 1 for i in idx)
    b = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(b)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """CSR surface: converted to COO internally (BCOO is jax's native
    format; the reference's CSR kernels are format-specific GPU code)."""
    crows = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(np.stack([rows, cols]), values, shape, dtype)


def is_sparse(x) -> bool:
    return isinstance(x, SparseCooTensor)


def to_dense(x):
    return x.to_dense() if is_sparse(x) else as_tensor(x)


def _binary(a, b, op):
    ab = a._b.sum_duplicates() if is_sparse(a) else None
    bb = b._b.sum_duplicates() if is_sparse(b) else None
    if ab is not None and bb is not None:
        dense = op(ab.todense(), bb.todense())
        return SparseCooTensor(jsparse.BCOO.fromdense(dense))
    raise TypeError("sparse binary ops need two SparseCooTensors")


def add(a, b):
    return _binary(a, b, jnp.add)


def multiply(a, b):
    return _binary(a, b, jnp.multiply)


def matmul(a, b):
    """sparse @ dense -> dense Tensor (the TPU-meaningful product);
    gradient flows into the dense operand."""
    if not is_sparse(a):
        raise TypeError("first operand must be sparse")
    dense = as_tensor(b)
    bcoo = a._b
    from ..autograd.function import apply
    return apply(lambda d: bcoo @ d, dense, name="sparse_matmul")


# -- unary value-wise ops (reference sparse_ops.yaml: applied to the stored
# values; the implicit zeros keep their sparsity). When the input carries a
# live autograd edge on its values (`_values_tensor`, set by the sparse
# conv/pool functionals), the op threads it so gradient chains survive
# stacked sparse layers (conv -> relu -> conv). -----------------------------

def _grad_values(x):
    """The differentiable values Tensor for x (falls back to raw data)."""
    vt = getattr(x, "_values_tensor", None)
    return vt if vt is not None else Tensor(x._b.data, stop_gradient=True)


def _unary(jfn, name):
    def op(x, *a, **kw):
        if not is_sparse(x):
            raise TypeError(f"sparse.{name} expects a SparseCooTensor")
        b = x._b
        from ..autograd.function import apply
        out_vals = apply(lambda v: jfn(v, *a, **kw), _grad_values(x),
                         name=f"sparse_{name}")
        out = SparseCooTensor(
            jsparse.BCOO((out_vals._data, b.indices), shape=b.shape))
        out._values_tensor = out_vals
        return out
    op.__name__ = name
    return op


relu = _unary(lambda v: jnp.maximum(v, 0), "relu")


abs = _unary(jnp.abs, "abs")
sin = _unary(jnp.sin, "sin")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
atanh = _unary(jnp.arctanh, "atanh")
acos = _unary(jnp.arccos, "acos")
acosh = _unary(jnp.arccosh, "acosh")
sqrt = _unary(jnp.sqrt, "sqrt")
square = _unary(jnp.square, "square")
log1p = _unary(jnp.log1p, "log1p")
expm1 = _unary(jnp.expm1, "expm1")
neg = _unary(jnp.negative, "neg")
relu6 = _unary(lambda v: jnp.clip(v, 0, 6), "relu6")
isnan = _unary(jnp.isnan, "isnan")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")


def is_same_shape(x, y):
    """Shape equality across sparse/dense operands (reference:
    python/paddle/sparse/unary.py is_same_shape)."""
    xs = tuple(x._b.shape) if is_sparse(x) else tuple(x.shape)
    ys = tuple(y._b.shape) if is_sparse(y) else tuple(y.shape)
    return xs == ys


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA of a sparse matrix (reference: sparse/unary.py
    pca_lowrank over the dense kernel): densify and delegate — the
    randomized range finder is dense-iterative either way on TPU."""
    from ..ops.linalg import pca_lowrank as dense_pca
    return dense_pca(to_dense(x) if is_sparse(x) else x, q=q,
                     center=center, niter=niter, name=name)


def leaky_relu(x, negative_slope=0.01):
    return _unary(lambda v: jnp.where(v >= 0, v, negative_slope * v),
                  "leaky_relu")(x)


def pow(x, factor):
    return _unary(lambda v: jnp.power(v, factor), "pow")(x)


def scale(x, scale, bias=0.0, bias_after_scale=True):
    """Reference sparse scale: bias applies to stored values only."""
    def f(v):
        return v * scale + bias if bias_after_scale else (v + bias) * scale
    return _unary(f, "scale")(x)


def cast(x, index_dtype=None, value_dtype=None):
    if not is_sparse(x):
        raise TypeError("sparse.cast expects a SparseCooTensor")
    b = x._b
    from ..core.dtype import dtype_from_any
    idx = b.indices if index_dtype is None else \
        b.indices.astype(dtype_from_any(index_dtype).np_dtype)
    val = b.data if value_dtype is None else \
        b.data.astype(dtype_from_any(value_dtype).np_dtype)
    return SparseCooTensor(jsparse.BCOO((val, idx), shape=b.shape))


def coalesce(x):
    return x.coalesce()


# -- binaries / reductions / manipulation ------------------------------------

def subtract(a, b):
    return _binary(a, b, jnp.subtract)


def divide(a, b):
    return _binary(a, b, jnp.true_divide)


def divide_scalar(x, scalar):
    return _unary(lambda v: v / scalar, "divide_scalar")(x)


def sum(x, axis=None, keepdim=False, dtype=None):
    """Reduce over the dense view (XLA has no sparse layouts; the honest
    lowering is gather-free dense reduction). Returns a dense Tensor."""
    if not is_sparse(x):
        raise TypeError("sparse.sum expects a SparseCooTensor")
    out = jnp.sum(x._b.todense(), axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..core.dtype import dtype_from_any
        out = out.astype(dtype_from_any(dtype).np_dtype)
    return Tensor(out, stop_gradient=True)


def reshape(x, shape):
    if not is_sparse(x):
        raise TypeError("sparse.reshape expects a SparseCooTensor")
    return SparseCooTensor(jsparse.BCOO.fromdense(
        jnp.reshape(x._b.todense(), tuple(shape))))


def transpose(x, perm):
    if not is_sparse(x):
        raise TypeError("sparse.transpose expects a SparseCooTensor")
    return SparseCooTensor(jsparse.BCOO.fromdense(
        jnp.transpose(x._b.todense(), tuple(perm))))


_pyslice = slice  # captured before the sparse `slice` op shadows it


def slice(x, axes, starts, ends):
    if not is_sparse(x):
        raise TypeError("sparse.slice expects a SparseCooTensor")
    dense = x._b.todense()
    idx = [_pyslice(None)] * dense.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[int(ax)] = _pyslice(int(s), int(e))
    return SparseCooTensor(jsparse.BCOO.fromdense(dense[tuple(idx)]))


def full_like(x, fill_value, dtype=None):
    if not is_sparse(x):
        raise TypeError("sparse.full_like expects a SparseCooTensor")
    b = x._b
    val = jnp.full_like(b.data, fill_value)
    if dtype is not None:
        from ..core.dtype import dtype_from_any
        val = val.astype(dtype_from_any(dtype).np_dtype)
    return SparseCooTensor(jsparse.BCOO((val, b.indices), shape=b.shape))


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta * input + alpha * (sparse x @ dense y) -> dense Tensor."""
    prod = matmul(x, y)
    from ..autograd.function import apply
    return apply(lambda i, p: beta * i + alpha * p, as_tensor(input), prod,
                 name="sparse_addmm")


def mv(x, vec):
    """sparse [M, N] @ dense [N] -> dense [M]."""
    return matmul(x, vec)


def masked_matmul(x, y, mask):
    """(dense x @ dense y) sampled at `mask`'s sparsity pattern (reference
    sparse masked_matmul — the SDDMM primitive)."""
    if not is_sparse(mask):
        raise TypeError("mask must be a SparseCooTensor")
    xa, ya = as_tensor(x)._data, as_tensor(y)._data
    b = mask._b
    rows, cols = b.indices[:, 0], b.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xa[rows, :], jnp.swapaxes(ya, 0, 1)[cols, :])
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape))


def softmax(x, axis=-1):
    """Row softmax over stored values only (implicit zeros act as -inf,
    reference sparse softmax semantics); 2-D COO. Threads the values
    autograd edge like the _unary ops."""
    if not is_sparse(x):
        raise TypeError("sparse.softmax expects a SparseCooTensor")
    has_edge = getattr(x, "_values_tensor", None) is not None
    b = x._b if has_edge else x._b.sum_duplicates()
    if len(b.shape) != 2 or axis not in (-1, 1):
        raise NotImplementedError("sparse.softmax: 2-D, last axis only")
    rows = b.indices[:, 0]
    n_rows = b.shape[0]

    def f(v):
        vals = v.astype(jnp.float32)
        row_max = jax.ops.segment_max(vals, rows, num_segments=n_rows)
        e = jnp.exp(vals - jnp.take(row_max, rows))
        denom = jax.ops.segment_sum(e, rows, num_segments=n_rows)
        out = e / jnp.take(jnp.maximum(denom, 1e-30), rows)
        return out.astype(v.dtype)

    from ..autograd.function import apply
    out_vals = apply(f, _grad_values(x), name="sparse_softmax")
    out = SparseCooTensor(jsparse.BCOO((out_vals._data, b.indices),
                                       shape=b.shape))
    out._values_tensor = out_vals
    return out


def to_sparse_coo(x, sparse_dim=None):
    """Dense Tensor -> SparseCooTensor (reference Tensor.to_sparse_coo)."""
    return SparseCooTensor(jsparse.BCOO.fromdense(as_tensor(x)._data))


def to_sparse_csr(x):
    """CSR view: returned as the COO wrapper (BCOO is the jax layout); the
    CSR accessors live on the result's crows()/cols()."""
    coo = to_sparse_coo(x)
    b = coo._b.sum_duplicates()
    rows = np.asarray(b.indices[:, 0])
    crows = np.zeros(b.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    coo.crows = lambda: Tensor(jnp.asarray(crows), stop_gradient=True)
    coo.cols = lambda: Tensor(b.indices[:, 1], stop_gradient=True)
    return coo


# layer/functional surface (imported last: sparse.nn uses this module)
from . import nn  # noqa: E402,F401
