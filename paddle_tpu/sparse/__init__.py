"""paddle.sparse equivalent (reference: python/paddle/sparse/ —
creation.py sparse_coo_tensor/sparse_csr_tensor, unary/binary ops, nn).

TPU design: sparse values ride jax.experimental.sparse.BCOO — XLA lowers
sparse-dense matmuls to gather/scatter programs, which is the honest TPU
story (no sparse tensor cores). The SparseTensor wrapper keeps the
reference surface: indices()/values()/to_dense()/nnz, add/mul, matmul,
relu, and coalesce.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, as_tensor

__all__ = ["SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor",
           "to_dense", "add", "multiply", "matmul", "relu", "coalesce",
           "is_sparse"]


class SparseCooTensor:
    """COO sparse tensor over BCOO (reference core SparseCooTensor)."""

    def __init__(self, bcoo: "jsparse.BCOO"):
        self._b = bcoo

    # -- reference accessors -------------------------------------------------
    @property
    def shape(self):
        return list(self._b.shape)

    def indices(self) -> Tensor:
        return Tensor(jnp.swapaxes(self._b.indices, 0, 1),
                      stop_gradient=True)  # [ndim, nnz] reference layout

    def values(self) -> Tensor:
        return Tensor(self._b.data, stop_gradient=True)

    @property
    def nnz(self) -> int:
        return int(self._b.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._b.todense(), stop_gradient=True)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._b.sum_duplicates())

    def is_sparse(self):
        return True

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """indices: [ndim, nnz] (reference layout); values: [nnz]."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor)
                     else indices)
    val = as_tensor(values)._data
    if dtype is not None:
        from ..core.dtype import dtype_from_any
        val = val.astype(dtype_from_any(dtype).np_dtype)
    if shape is None:
        shape = tuple(int(i.max()) + 1 for i in idx)
    b = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(b)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """CSR surface: converted to COO internally (BCOO is jax's native
    format; the reference's CSR kernels are format-specific GPU code)."""
    crows = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(np.stack([rows, cols]), values, shape, dtype)


def is_sparse(x) -> bool:
    return isinstance(x, SparseCooTensor)


def to_dense(x):
    return x.to_dense() if is_sparse(x) else as_tensor(x)


def _binary(a, b, op):
    ab = a._b.sum_duplicates() if is_sparse(a) else None
    bb = b._b.sum_duplicates() if is_sparse(b) else None
    if ab is not None and bb is not None:
        dense = op(ab.todense(), bb.todense())
        return SparseCooTensor(jsparse.BCOO.fromdense(dense))
    raise TypeError("sparse binary ops need two SparseCooTensors")


def add(a, b):
    return _binary(a, b, jnp.add)


def multiply(a, b):
    return _binary(a, b, jnp.multiply)


def matmul(a, b):
    """sparse @ dense -> dense Tensor (the TPU-meaningful product);
    gradient flows into the dense operand."""
    if not is_sparse(a):
        raise TypeError("first operand must be sparse")
    dense = as_tensor(b)
    bcoo = a._b
    from ..autograd.function import apply
    return apply(lambda d: bcoo @ d, dense, name="sparse_matmul")


def relu(x):
    if not is_sparse(x):
        raise TypeError("sparse.relu expects a SparseCooTensor")
    b = x._b
    return SparseCooTensor(jsparse.BCOO((jnp.maximum(b.data, 0), b.indices),
                                        shape=b.shape))
