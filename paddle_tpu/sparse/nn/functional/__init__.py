"""Sparse 3-D convolution / pooling functionals (reference:
python/paddle/sparse/nn/functional/conv.py:199 conv3d, :305 subm_conv3d,
pooling.py:22 max_pool3d; CUDA kernels paddle/phi/kernels/sparse/
conv_kernel.h, gpu/conv_kernel.cu, pool_kernel.cu).

TPU-native design: the reference builds a "rulebook" (per kernel-offset
gather/scatter index pairs) on device with hash tables. Here the rulebook
is built ONCE on host from the (host-resident) COO coordinates — sparse
topologies change per sample, not per step, and coordinates are tiny next
to features — and the FEATURE math runs as pure jnp over the rulebook:
one [C, M] matmul per live kernel offset plus a segment-sum scatter, which
is exactly the dense-GEMM-per-offset formulation the MXU wants. Gradients
flow to values/weight/bias through the framework's normal vjp (the
rulebook indices are constants of the traced program).

Layouts match the reference: x is a SparseCooTensor [N, D, H, W, C] with
sparse (N, D, H, W) and dense channel values [nnz, C]; weight is
[kd, kh, kw, C, M]; only data_format="NDHWC" and groups=1 are supported
(the reference's sparse conv has the same restrictions,
sparse/nn/layer/conv.py:31).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ....core.tensor import Tensor, as_tensor
from ....autograd.function import apply
from ... import SparseCooTensor

__all__ = ["conv3d", "subm_conv3d", "max_pool3d", "conv2d",
           "subm_conv2d", "relu", "relu6", "leaky_relu", "softmax",
           "attention"]


def _triple(v):
    if isinstance(v, (list, tuple)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v}")
        return tuple(int(i) for i in v)
    return (int(v),) * 3


def _coords_values(x: SparseCooTensor):
    """(coords [nnz, 4], values Tensor [nnz, C]). When x carries a live
    autograd edge on its values (an upstream sparse op's output), keep it
    — and skip sum_duplicates, whose row reorder would desynchronize the
    edge from the coordinates (our ops always emit unique coords)."""
    vt = getattr(x, "_values_tensor", None)
    b = x._b if vt is not None else x._b.sum_duplicates()
    coords = np.asarray(b.indices)          # [nnz, 4] (n, d, h, w)
    if coords.shape[1] != 4:
        raise ValueError(
            "sparse conv3d expects a SparseCooTensor with sparse "
            "(N, D, H, W) and dense channel values [nnz, C]; got sparse "
            f"rank {coords.shape[1]}")
    vals = vt if vt is not None else Tensor(b.data, stop_gradient=True)
    if vals.ndim == 1:
        from ....ops.manipulation import reshape
        vals = reshape(vals, [-1, 1])
    return coords, vals


def _offset_maps(coords, spatial_out, kernel, stride, padding, dilation):
    """Yield (offset_key, in_rows, out_coords [k, 4]) per kernel offset —
    the single copy of the mapping math both rulebook modes share."""
    kd, kh, kw = kernel
    n = coords[:, 0]
    dhw = coords[:, 1:4].astype(np.int64)
    pads = np.array(padding)
    strides = np.array(stride)
    dils = np.array(dilation)
    bound = np.array(spatial_out)
    for oi in range(kd):
        for oj in range(kh):
            for ok in range(kw):
                top = dhw + pads - np.array([oi, oj, ok]) * dils
                q, r = np.divmod(top, strides)
                ok_mask = (r == 0).all(1) & (q >= 0).all(1) & \
                    (q < bound).all(1)
                rows = np.nonzero(ok_mask)[0]
                oc = np.concatenate([n[rows, None], q[rows]], 1)
                yield (oi, oj, ok), rows, oc


_RULEBOOK_CACHE: dict = {}
_RULEBOOK_CACHE_MAX = 64


def _rulebook(coords, spatial_in, kernel, stride, padding, dilation,
              out_coords=None, ceil_mode=False):
    """Per-offset (in_rows, out_rows) gather/scatter pairs + the output
    coordinate set (reference conv_kernel.h ProductRuleBook). Memoized on
    the coordinate set + geometry: sparse topologies repeat across layers
    and steps, and the host-side set/dict build would otherwise serialize
    against device compute every forward (the reference caches rulebooks
    the same way, keyed by SubmConv3D's `key`)."""
    ck = (coords.tobytes(), coords.shape, spatial_in, kernel, stride,
          padding, dilation,
          None if out_coords is None else out_coords.tobytes(), ceil_mode)
    hit = _RULEBOOK_CACHE.get(ck)
    if hit is not None:
        return hit

    def odim(inp, p, d, k, s):
        num = inp + 2 * p - d * (k - 1) - 1
        return (num + s - 1) // s + 1 if ceil_mode else num // s + 1

    spatial_out = tuple(
        odim(i, p, d, k, s) for i, p, d, k, s in
        zip(spatial_in, padding, dilation, kernel, stride))

    if out_coords is None:
        sites = set()
        raw = []
        for key, rows, oc in _offset_maps(coords, spatial_out, kernel,
                                          stride, padding, dilation):
            raw.append((key, rows, oc))
            for t in map(tuple, oc):
                sites.add(t)
        out_list = sorted(sites)
        out_index = {t: i for i, t in enumerate(out_list)}
        book = [(key, rows,
                 np.asarray([out_index[tuple(t)] for t in oc], np.int64))
                for key, rows, oc in raw if len(rows)]
        out_arr = np.asarray(out_list, np.int64).reshape(-1, 4)
    else:
        # submanifold: outputs fixed to the given coordinate set
        out_index = {tuple(t): i
                     for i, t in enumerate(map(tuple, out_coords))}
        book = []
        for key, rows, oc in _offset_maps(coords, spatial_out, kernel,
                                          stride, padding, dilation):
            hits = [(rr, out_index[tuple(t)])
                    for rr, t in zip(rows, map(tuple, oc))
                    if tuple(t) in out_index]
            if hits:
                rr, outs = zip(*hits)
                book.append((key, np.asarray(rr, np.int64),
                             np.asarray(outs, np.int64)))
        out_arr = np.asarray(out_coords, np.int64).reshape(-1, 4)

    result = (book, out_arr, spatial_out)
    if len(_RULEBOOK_CACHE) >= _RULEBOOK_CACHE_MAX:
        _RULEBOOK_CACHE.pop(next(iter(_RULEBOOK_CACHE)))
    _RULEBOOK_CACHE[ck] = result
    return result


def _conv_impl(x, weight, bias, stride, padding, dilation, groups,
               data_format, submanifold):
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports data_format='NDHWC' only "
                         "(reference restriction)")
    if groups != 1:
        raise ValueError("sparse conv3d supports groups=1 only "
                         "(reference sparse/nn/layer/conv.py:31)")
    if submanifold and _triple(stride) != (1, 1, 1):
        raise ValueError(
            "subm_conv3d requires stride=1: submanifold convolution is "
            "defined on the input's own coordinate set, which a strided "
            "output grid cannot index")
    w_t = as_tensor(weight)
    kd, kh, kw, cin, m = w_t.shape
    nb, din, hin, win, c = x.shape
    if c != cin:
        raise ValueError(f"weight expects {cin} input channels, x has {c}")
    coords, vals = _coords_values(x)
    book, out_coords, (dout, hout, wout) = _rulebook(
        coords, (din, hin, win), (kd, kh, kw), _triple(stride),
        _triple(padding), _triple(dilation),
        out_coords=coords if submanifold else None)
    out_nnz = len(out_coords)
    args = [vals, w_t] + ([as_tensor(bias)] if bias is not None else [])

    def f(v, w, *b):
        out = jnp.zeros((out_nnz, m), jnp.float32)
        for (oi, oj, ok), rows, outs in book:
            contrib = v[rows].astype(jnp.float32) @ \
                w[oi, oj, ok].astype(jnp.float32)
            out = out.at[outs].add(contrib)
        if b:
            out = out + b[0].astype(jnp.float32)
        return out.astype(v.dtype)

    out_vals = apply(lambda *a: f(*a), *args,
                     name="subm_conv3d" if submanifold else "sparse_conv3d")
    if submanifold:
        shape = (nb, din, hin, win, m)
    else:
        shape = (nb, dout, hout, wout, m)
    b = jsparse.BCOO((out_vals._data, jnp.asarray(out_coords)), shape=shape)
    out = SparseCooTensor(b)
    out._values_tensor = out_vals  # keeps the autograd edge reachable
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None) -> SparseCooTensor:
    """Sparse conv3d (reference functional/conv.py:199)."""
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups,
                      data_format, submanifold=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None,
                name=None) -> SparseCooTensor:
    """Submanifold sparse conv3d: output sites == input sites (reference
    functional/conv.py:305)."""
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups,
                      data_format, submanifold=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None) -> SparseCooTensor:
    """Sparse max pooling over occupied sites only (reference
    functional/pooling.py:22, pool_kernel.cu MaxPool): each output site
    takes the per-channel max over its CONTRIBUTING input sites — empty
    positions do not participate (they are not zeros)."""
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only")
    kernel = _triple(kernel_size)
    stride = _triple(stride if stride is not None else kernel_size)
    padding = _triple(padding)
    nb, din, hin, win, c = x.shape
    coords, vals = _coords_values(x)
    book, out_coords, (dout, hout, wout) = _rulebook(
        coords, (din, hin, win), kernel, stride, padding, (1, 1, 1),
        ceil_mode=ceil_mode)
    out_nnz = len(out_coords)

    def f(v):
        vf = v.astype(jnp.float32)
        out = jnp.full((out_nnz, vf.shape[-1]), -jnp.inf, jnp.float32)
        for _, rows, outs in book:
            out = out.at[outs].max(vf[rows])
        return out.astype(v.dtype)

    out_vals = apply(f, vals, name="sparse_max_pool3d")
    b = jsparse.BCOO((out_vals._data, jnp.asarray(out_coords)),
                     shape=(nb, dout, hout, wout, c))
    out = SparseCooTensor(b)
    out._values_tensor = out_vals
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None) -> SparseCooTensor:
    """Sparse conv2d (reference functional/conv.py conv2d): lifted onto
    the 3-D rulebook machinery with a unit depth axis."""
    return _conv2d_impl(x, weight, bias, stride, padding, dilation, groups,
                        data_format, submanifold=False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None,
                name=None) -> SparseCooTensor:
    """Submanifold sparse conv2d (reference functional/conv.py
    subm_conv2d)."""
    return _conv2d_impl(x, weight, bias, stride, padding, dilation, groups,
                        data_format, submanifold=True)


def _conv2d_impl(x, weight, bias, stride, padding, dilation, groups,
                 data_format, submanifold):
    import numpy as np
    import jax.numpy as jnp

    from ... import SparseCooTensor, sparse_coo_tensor
    from ....core.tensor import as_tensor

    if data_format != "NHWC":
        raise ValueError("sparse conv2d is NHWC (reference contract)")
    b = x._b
    n, h, w, c = b.shape
    # lift [N, H, W, C] -> [N, 1, H, W, C]
    idx = jnp.asarray(b.indices)
    idx3 = jnp.concatenate([idx[:, :1],
                            jnp.zeros((idx.shape[0], 1), idx.dtype),
                            idx[:, 1:]], axis=1)
    x3 = sparse_coo_tensor(idx3.T, x.values(), (n, 1, h, w, c))
    kw = as_tensor(weight)
    if kw.ndim == 4:  # [kh, kw, C, M] -> [1, kh, kw, C, M]
        from .... import ops
        kw = ops.unsqueeze(kw, 0)

    def lift(v, neutral):
        a = np.atleast_1d(v)
        if a.size == 1:
            a = np.repeat(a, 2)
        return [neutral] + [int(e) for e in a[:2]]

    fn = subm_conv3d if submanifold else conv3d
    out3 = fn(x3, kw, bias, lift(stride, 1), lift(padding, 0),
              lift(dilation, 1), groups, "NDHWC")
    ob = out3._b
    oidx = jnp.asarray(ob.indices)
    oidx2 = jnp.concatenate([oidx[:, :1], oidx[:, 2:]], axis=1)
    shp = ob.shape
    return sparse_coo_tensor(oidx2.T, out3.values(),
                             (shp[0], shp[2], shp[3], shp[4]))


def relu(x, name=None):
    from ... import relu as _op
    return _op(x)


def relu6(x, name=None):
    from ... import relu6 as _op
    return _op(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from ... import leaky_relu as _op
    return _op(x, negative_slope)


def softmax(x, axis=-1, name=None):
    from ... import softmax as _op
    return _op(x, axis)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference functional/transformer.py:22): the
    CSR sparse_mask carries the attended positions; masks add before the
    sparse softmax. Delegates to the framework's CSR sparse-attention
    path."""
    from ....nn.functional import sparse_attention as dense_entry
    from ....core.tensor import as_tensor

    q = as_tensor(query)
    crows = as_tensor(sparse_mask.crows()) if hasattr(sparse_mask, "crows") \
        else as_tensor(sparse_mask[0])
    cols = as_tensor(sparse_mask.cols()) if hasattr(sparse_mask, "cols") \
        else as_tensor(sparse_mask[1])
    return dense_entry(q, as_tensor(key), as_tensor(value), crows, cols,
                       key_padding_mask=key_padding_mask,
                       attn_mask=attn_mask)
