"""paddle.sparse.nn layers (reference: python/paddle/sparse/nn/layer/ —
conv.py Conv3D :239, SubmConv3D :509, pooling.py MaxPool3D :20, plus the
activation layers)."""

from __future__ import annotations

import math

from ...nn.layer import Layer
from ...nn import initializer as I
from . import functional  # noqa: F401
from . import functional as F

__all__ = ["Conv3D", "SubmConv3D", "MaxPool3D", "ReLU", "Softmax"]


class _Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse Conv3D supports groups=1 only "
                             "(reference sparse/nn/layer/conv.py:31)")
        ks = F._triple(kernel_size)
        self._kernel_size = list(ks)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels * ks[0] * ks[1] * ks[2]
        std = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(ks) + [in_channels, out_channels], weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = self.create_parameter(
            [out_channels], bias_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std)) \
            if bias_attr is not False else None


class Conv3D(_Conv3D):
    """Sparse conv3d layer (reference sparse/nn/layer/conv.py:239)."""

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class SubmConv3D(_Conv3D):
    """Submanifold sparse conv3d layer (reference conv.py:509)."""

    def __init__(self, *args, key=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._key = key

    def forward(self, x):
        return F.subm_conv3d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups,
                             self._data_format, key=self._key)


class MaxPool3D(Layer):
    """Sparse max pooling layer (reference sparse/nn/layer/pooling.py:20)."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self._args)


class ReLU(Layer):
    def forward(self, x):
        from .. import relu
        return relu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from .. import softmax
        return softmax(x, self._axis)
