"""paddle.sparse.nn layers (reference: python/paddle/sparse/nn/layer/ —
conv.py Conv3D :239, SubmConv3D :509, pooling.py MaxPool3D :20, plus the
activation layers)."""

from __future__ import annotations

import math

from ...nn.layer import Layer
from ...nn import initializer as I
from . import functional  # noqa: F401
from . import functional as F

__all__ = ["Conv3D", "SubmConv3D", "MaxPool3D", "ReLU", "Softmax",
           "Conv2D", "SubmConv2D", "ReLU6", "LeakyReLU", "BatchNorm",
           "SyncBatchNorm"]


class _Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse Conv3D supports groups=1 only "
                             "(reference sparse/nn/layer/conv.py:31)")
        ks = F._triple(kernel_size)
        self._kernel_size = list(ks)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels * ks[0] * ks[1] * ks[2]
        std = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(ks) + [in_channels, out_channels], weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = self.create_parameter(
            [out_channels], bias_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std)) \
            if bias_attr is not False else None


class Conv3D(_Conv3D):
    """Sparse conv3d layer (reference sparse/nn/layer/conv.py:239)."""

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class SubmConv3D(_Conv3D):
    """Submanifold sparse conv3d layer (reference conv.py:509)."""

    def __init__(self, *args, key=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._key = key

    def forward(self, x):
        return F.subm_conv3d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups,
                             self._data_format, key=self._key)


class MaxPool3D(Layer):
    """Sparse max pooling layer (reference sparse/nn/layer/pooling.py:20)."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self._args)


class ReLU(Layer):
    def forward(self, x):
        from .. import relu
        return relu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from .. import softmax
        return softmax(x, self._axis)


class _Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse Conv2D supports groups=1 only")
        if isinstance(kernel_size, int):
            ks = [kernel_size, kernel_size]
        else:
            ks = [int(k) for k in kernel_size]
            if len(ks) != 2:
                raise ValueError(f"Conv2D kernel_size needs 2 values, got "
                                 f"{kernel_size}")
        self._kernel_size = list(ks)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels * ks[0] * ks[1]
        std = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            list(ks) + [in_channels, out_channels], weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = self.create_parameter(
            [out_channels], bias_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std)) \
            if bias_attr is not False else None


class Conv2D(_Conv2D):
    """Sparse conv2d layer (reference sparse/nn/layer/conv.py Conv2D)."""

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class SubmConv2D(_Conv2D):
    """Submanifold sparse conv2d (reference conv.py SubmConv2D)."""

    def __init__(self, *args, key=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._key = key

    def forward(self, x):
        return F.subm_conv2d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups,
                             self._data_format, key=self._key)


class ReLU6(Layer):
    def forward(self, x):
        from .. import relu6
        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        from .. import leaky_relu
        return leaky_relu(x, self._slope)


class BatchNorm(Layer):
    """BatchNorm over the nnz values of a channel-last SparseCooTensor
    (reference: sparse/nn/layer/norm.py:24 — dense BatchNorm1D applied to
    the values; the sparsity pattern is untouched)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr,
                               use_global_stats=use_global_stats)

    def forward(self, x):
        from .. import SparseCooTensor, is_sparse
        from jax.experimental import sparse as jsparse

        if not is_sparse(x):
            raise TypeError("sparse.nn.BatchNorm expects a SparseCooTensor")
        vals = self._bn(x.values())
        b = x._b
        out = SparseCooTensor(jsparse.BCOO((vals._data, b.indices),
                                           shape=b.shape))
        out._values_tensor = vals
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BatchNorm (reference sparse/nn/layer/norm.py
    SyncBatchNorm). Stats sync rides the dense SyncBatchNorm semantics:
    under GSPMD, batch stats of replicated modules reduce automatically;
    the single-controller path equals BatchNorm."""
    pass
