"""`paddle.summary` and `paddle.flops` (reference:
python/paddle/hapi/model_summary.py and hapi/dynamic_flops.py).

summary: forward-hook walk printing per-layer output shapes and parameter
counts. flops: XLA's own cost analysis of the traced forward — exact for
the whole program rather than a per-op estimate table."""

from __future__ import annotations

import numpy as np

__all__ = ['summary', 'flops']


def _make_input(shape, dtype):
    import paddle_tpu as paddle

    shape = [1 if (s is None or s == -1) else int(s) for s in shape]
    if dtype and ('int' in str(dtype)):
        return paddle.to_tensor(np.zeros(shape, dtype=str(dtype)))
    return paddle.to_tensor(np.zeros(shape, np.float32))


def summary(net, input_size=None, dtypes=None, input=None):
    """Print (and return) the per-layer summary table.

    input_size: tuple or list of tuples (batch dim may be None/-1).
    Returns {'total_params': N, 'trainable_params': M}."""
    import paddle_tpu as paddle
    from ..nn.layer import Layer

    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = ([input_size] if isinstance(input_size[0], (int, type(None)))
                 else list(input_size))
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes] * len(sizes)
        inputs = [_make_input(s, d) for s, d in zip(sizes, dts)]
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    rows = []
    hooks = []

    def add_hook(layer, name):
        def hook(lyr, ins, out):
            outs = out if isinstance(out, (tuple, list)) else (out,)
            shape = [list(o.shape) for o in outs
                     if hasattr(o, 'shape')]
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr._parameters.values()
                           if p is not None)
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}",
                         shape[0] if len(shape) == 1 else shape, n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers(include_self=False):
        add_hook(sub, name)
    if not hooks:  # plain layer with no children
        add_hook(net, type(net).__name__)

    was_training = getattr(net, 'training', False)
    net.eval()
    try:
        with paddle.no_grad():
            net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    name_w = max([len(r[0]) for r in rows] + [12]) + 2
    line = "-" * (name_w + 40)
    out_lines = [line, f"{'Layer (type)':<{name_w}}{'Output Shape':<24}"
                 f"{'Param #':>10}", line]
    for name, shape, n in rows:
        out_lines.append(f"{name:<{name_w}}{str(shape):<24}{n:>10,}")
    out_lines += [line,
                  f"Total params: {total:,}",
                  f"Trainable params: {trainable:,}",
                  f"Non-trainable params: {total - trainable:,}",
                  line]
    print("\n".join(out_lines))
    return {'total_params': total, 'trainable_params': trainable}


def flops(net, input_size=None, custom_ops=None, print_detail=False,
          inputs=None):
    """FLOPs of one forward pass, from XLA's cost analysis of the traced
    program (reference dynamic_flops.py estimates per-op; the compiler's
    count covers everything it actually emits)."""
    from ..cost_model import CostModel

    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        sizes = ([input_size] if isinstance(input_size[0], (int, type(None)))
                 else list(input_size))
        inputs = [_make_input(s, None) for s in sizes]
    elif not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    was_training = getattr(net, 'training', False)
    net.eval()
    try:
        analysis = CostModel().static_cost(lambda *xs: net(*xs), *inputs)
    finally:
        if was_training:
            net.train()
    total = int(analysis.get('flops', 0))
    if print_detail:
        print(f"Total Flops: {total:,}")
        for k in sorted(analysis):
            if k.startswith('flops'):
                print(f"  {k}: {analysis[k]}")
    return total
