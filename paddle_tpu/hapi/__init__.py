"""High-level training API (reference: python/paddle/hapi/)."""

from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau)

__all__ = ["Model", "callbacks"]
