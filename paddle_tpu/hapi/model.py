"""hapi Model: high-level train/eval/predict loops (reference:
python/paddle/hapi/model.py:1054 `Model`, fit :1756).

TPU-first design: `prepare()` records optimizer/loss/metrics and the whole
train step (forward + backward + optimizer update) is compiled once with
`paddle.jit.to_static` — one XLA program per step instead of the reference's
op-by-op dygraph loop. Metrics stream on host from the step's outputs.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from ..core.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric.metrics import Metric
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class Model:
    """Network wrapper with fit/evaluate/predict (reference Model:1054).

    Usage matches the reference::

        model = paddle.Model(network)
        model.prepare(optimizer, loss, metrics)
        model.fit(train_ds, eval_ds, batch_size=64, epochs=2)
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._amp_level = "O0"
        self.stop_training = False
        self._save_dir = None
        self._train_step = None
        self._eval_step = None
        self._predict_step = None

    # -- configuration ------------------------------------------------------

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be callable or a Layer")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O0")
        if self._amp_level == "O2" and optimizer is not None:
            self.network, self._optimizer = paddle.amp.decorate(
                self.network, self._optimizer, level="O2", dtype="bfloat16")
        self._train_step = None
        self._eval_step = None
        self._predict_step = None

    # -- single-batch API ---------------------------------------------------

    def _split_batch(self, data):
        """[inputs..., labels...] split by declared specs or loss arity."""
        data = _to_list(data)
        if self._inputs:
            n_in = len(self._inputs)
        elif self._loss is not None and len(data) > 1:
            n_in = len(data) - max(len(self._labels), 1)
        else:
            n_in = len(data)
        return data[:n_in], data[n_in:]

    def _as_tensors(self, xs):
        return [x if isinstance(x, Tensor) else paddle.to_tensor(x)
                for x in xs]

    def _build_train_step(self, n_in, update):
        model = self

        def raw(*args):
            ins, labs = args[:n_in], args[n_in:]
            if model._amp_level == "O1":
                with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
                    outs = model.network(*ins)
            else:
                outs = model.network(*ins)
            outs_l = _to_list(outs)
            loss = model._loss(*(outs_l + list(labs)))
            loss.backward()  # accumulates into .grad when update is False
            if update:
                model._optimizer.step()
                model._optimizer.clear_grad()
            return tuple([loss] + outs_l)

        return paddle.jit.to_static(raw)

    def train_batch(self, inputs, labels=None, update=True):
        """One optimizer step (or gradient accumulation when update=False);
        returns [loss] (+ metric results). Reference Model.train_batch:1196."""
        ins = self._as_tensors(_to_list(inputs))
        labs = self._as_tensors(_to_list(labels))
        key = (len(ins), bool(update))
        if self._train_step is None:
            self._train_step = {}
        if key not in self._train_step:
            self._train_step[key] = self._build_train_step(len(ins), update)
        res = self._train_step[key](*ins, *labs)
        loss, outs = res[0], res[1:]
        self._update_metrics(outs, labs)
        m = [float(np.asarray(loss.numpy()).reshape(-1)[0])]
        return m if not self._metrics else (m, self._metric_results())

    def eval_batch(self, inputs, labels=None):
        ins = self._as_tensors(_to_list(inputs))
        labs = self._as_tensors(_to_list(labels))
        if self._eval_step is None or getattr(self, "_eval_n_in", None) != \
                len(ins):
            model = self
            n_in = len(ins)

            def raw(*args):
                with paddle.no_grad():
                    i, l = args[:n_in], args[n_in:]
                    outs = _to_list(model.network(*i))
                    loss = model._loss(*(outs + list(l))) \
                        if model._loss is not None else None
                return tuple(([loss] if loss is not None else []) + outs)

            self._eval_n_in = n_in
            self._eval_step = paddle.jit.to_static(raw)
        res = self._eval_step(*ins, *labs)
        if self._loss is not None:
            loss, outs = res[0], res[1:]
            out_m = [float(np.asarray(loss.numpy()).reshape(-1)[0])]
        else:
            loss, outs = None, res
            out_m = []
        self._update_metrics(outs, labs)
        return out_m if not self._metrics else (out_m,
                                                self._metric_results())

    def predict_batch(self, inputs):
        ins = self._as_tensors(_to_list(inputs))
        if self._predict_step is None:
            model = self

            def raw(*args):
                with paddle.no_grad():
                    return tuple(_to_list(model.network(*args)))

            self._predict_step = paddle.jit.to_static(raw)
        outs = self._predict_step(*ins)
        return [np.asarray(o.numpy()) for o in _to_list(outs)]

    def _update_metrics(self, outs, labs):
        for m in self._metrics:
            r = m.compute(*(_to_list(outs) + list(labs)))
            m.update(*[np.asarray(x.numpy()) if isinstance(x, Tensor) else x
                       for x in _to_list(r)])

    def _metric_results(self):
        return [m.accumulate() for m in self._metrics]

    # -- loops --------------------------------------------------------------

    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """Reference Model.fit:1756. Trains for `epochs`, evaluating every
        `eval_freq` epochs when eval_data is given."""
        assert self._optimizer is not None and self._loss is not None, \
            "call prepare(optimizer, loss) before fit"
        self._save_dir = save_dir
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers, False)
        try:
            steps = len(loader)
        except Exception:
            steps = None
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                epochs=epochs, steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir,
                                metrics=["loss"] + [m.name()
                                                    for m in self._metrics])
        self.stop_training = False
        cbks.on_begin("train")
        it = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_batch_begin("train", step, logs)
                ins, labs = self._split_batch(batch)
                # gradient accumulation: only every k-th batch steps the
                # optimizer; the others just add into .grad
                update = (step + 1) % accumulate_grad_batches == 0
                res = self.train_batch(ins, labs, update=update)
                logs = self._result_logs(res)
                cbks.on_batch_end("train", step, logs)
                it += 1
                if (num_iters is not None and it >= num_iters) or \
                        self.stop_training:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              num_workers=num_workers, _inner=True)
            if (num_iters is not None and it >= num_iters) or \
                    self.stop_training:
                break
        cbks.on_end("train", logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _inner=False):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        for m in self._metrics:
            m.reset()
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, batch_size=batch_size, log_freq=log_freq,
            verbose=verbose,
            metrics=["loss"] + [m.name() for m in self._metrics])
        try:
            n = len(loader)
        except Exception:
            n = None
        cbks.on_begin("eval", {"steps": n})
        logs = {}
        seen = 0
        for step, batch in enumerate(loader):
            cbks.on_batch_begin("eval", step, logs)
            ins, labs = self._split_batch(batch)
            res = self.eval_batch(ins, labs)
            logs = self._result_logs(res)
            cbks.on_batch_end("eval", step, logs)
            seen += 1
            if num_samples is not None and seen * batch_size >= num_samples:
                break
        cbks.on_end("eval", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose, metrics=[])
        cbks.on_begin("predict")
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_batch_begin("predict", step, {})
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
            cbks.on_batch_end("predict", step, {})
        cbks.on_end("predict")
        # transpose [steps][n_out] -> [n_out][steps]
        outs = list(map(list, zip(*outputs))) if outputs else []
        if stack_outputs:
            outs = [np.concatenate(o, axis=0) for o in outs]
        return outs

    def _result_logs(self, res):
        if self._metrics:
            losses, metrics = res
            logs = {"loss": losses[0]}
            for m, r in zip(self._metrics, metrics):
                names = _to_list(m.name())
                for nm, v in zip(names, _to_list(r)):
                    logs[nm] = v
            return logs
        return {"loss": res[0]}

    # -- persistence / info -------------------------------------------------

    def save(self, path, training=True):
        """path.pdparams (+ path.pdopt when training). Reference
        Model.save:1358."""
        import os
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        """Reference Model.load:1425."""
        import os
        state = paddle.load(path + ".pdparams")
        if skip_mismatch:
            own = self.network.state_dict()
            state = {k: v for k, v in state.items()
                     if k in own and list(own[k].shape) == list(v.shape)}
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(paddle.load(opt_path))
        self._train_step = None  # recompile against the restored state

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """Parameter-count summary (reference hapi/model_summary.py)."""
        rows = []
        total = 0
        trainable = 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            if not p.stop_gradient:
                trainable += n
            rows.append((name, list(p.shape), n))
        width = max([len(r[0]) for r in rows], default=20) + 2
        lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}"]
        lines += [f"{n:<{width}}{str(s):<20}{c:>12,}" for n, s, c in rows]
        lines += [f"Total params: {total:,}",
                  f"Trainable params: {trainable:,}",
                  f"Non-trainable params: {total - trainable:,}"]
        out = "\n".join(lines)
        print(out)
        return {"total_params": total, "trainable_params": trainable}
