"""hapi callbacks (reference: python/paddle/hapi/callbacks.py).

The callback protocol is identical to the reference's; ProgBarLogger prints
line-per-epoch summaries (TPU jobs run under schedulers where carriage-return
progress bars garble logs, so verbose=1 and 2 both use line output).
"""

from __future__ import annotations

import numbers
import os
import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "ReduceLROnPlateau"]


class Callback:
    """Base class (reference hapi/callbacks.py:131)."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, callback):
        self.callbacks.append(callback)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch=None, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch=None, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step=None, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step=None, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = callbacks or []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = [LRScheduler()] + list(cbks)
    if not any(isinstance(c, ModelCheckpoint) for c in cbks) and save_dir:
        cbks = list(cbks) + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return lst


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}" if isinstance(v, float) else str(v)
    try:
        import numpy as np
        a = np.asarray(v).reshape(-1)
        return f"{float(a[0]):.4f}" if a.size else str(v)
    except Exception:
        return str(v)


class ProgBarLogger(Callback):
    """Line-based train/eval logging (reference hapi/callbacks.py:300)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epochs = None
        self.steps = None

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch=None, logs=None):
        self.steps = self.params.get("steps")
        self.epoch = epoch
        self._seen = 0
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _log(self, prefix, step, logs):
        items = [f"step {step}" + (f"/{self.steps}" if self.steps else "")]
        items += [f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()]
        print(prefix + " - ".join(items))

    def on_train_batch_end(self, step, logs=None):
        self._seen += 1
        if self.verbose and self._seen % self.log_freq == 0:
            self._log("", step + 1, logs)

    def on_eval_begin(self, logs=None):
        if self.verbose:
            n = (logs or {}).get("steps")
            print(f"Eval begin{f' ({n} steps)' if n else ''}...")

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            print("Eval end - " +
                  " - ".join(f"{k}: {_fmt(v)}" for k, v in logs.items()))


class ModelCheckpoint(Callback):
    """Save every `save_freq` epochs into save_dir/{epoch} and final (reference
    hapi/callbacks.py ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps an LRScheduler attached to the optimizer (reference
    hapi/callbacks.py LRScheduler: by_step default)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference
    hapi/callbacks.py EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and ("acc" in monitor
                                                 or "auc" in monitor)):
            self._cmp = lambda cur, best: cur > best + self.min_delta
            self.best = float("-inf")
        else:
            self._cmp = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")

    def on_train_begin(self, logs=None):
        self.wait = 0
        if self.baseline is not None:
            self.best = self.baseline

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        try:
            import numpy as np
            cur = float(np.asarray(cur).reshape(-1)[0])
        except Exception:
            return
        if self._cmp(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} did not improve "
                          f"for {self.patience} evals (best {self.best:.5f})")


class ReduceLROnPlateau(Callback):
    """Multiply lr by `factor` when the monitored metric plateaus."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.wait = 0
        self.cooldown_counter = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self._better = lambda c, b: c > b + min_delta
            self.best = float("-inf")
        else:
            self._better = lambda c, b: c < b - min_delta
            self.best = float("inf")

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        import numpy as np
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    old = float(opt.get_lr())
                    new = max(old * self.factor, self.min_lr)
                    if hasattr(opt, "set_lr"):
                        opt.set_lr(new)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {old:.2e} -> {new:.2e}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """VisualDL scalar logging callback (reference hapi/callbacks.py
    VisualDL). The visualdl package is optional; absent, metrics fall
    back to a local jsonl the VisualDL UI (or any tool) can ingest."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self.epochs = None
        self.steps = None
        self.epoch = 0
        self._writers = {}
        self._step = {"train": 0, "eval": 0}

    def _writer(self, mode):
        if mode not in self._writers:
            try:
                from visualdl import LogWriter
                self._writers[mode] = LogWriter(self.log_dir)
            except ImportError:
                import json
                import os

                class _JsonlWriter:
                    def __init__(self, path):
                        os.makedirs(os.path.dirname(path), exist_ok=True)
                        self._f = open(path, "a")

                    def add_scalar(self, tag, value, step):
                        self._f.write(json.dumps(
                            {"tag": tag, "value": float(value),
                             "step": int(step)}) + "\n")
                        self._f.flush()

                    def close(self):
                        self._f.close()

                import os.path as osp
                self._writers[mode] = _JsonlWriter(
                    osp.join(self.log_dir, f"vdl_{mode}.jsonl"))
        return self._writers[mode]

    def _log(self, mode, logs, step):
        logs = logs or {}
        metrics = self.params.get("metrics") or list(logs)
        for k in metrics:
            if k in logs and isinstance(logs[k], (int, float)):
                self._writer(mode).add_scalar(f"{mode}/{k}", logs[k], step)

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch=None, logs=None):
        self.epoch = epoch or 0

    def on_train_batch_end(self, step, logs=None):
        self._step["train"] += 1
        if self._step["train"] % 10 == 0:
            self._log("train", logs, self._step["train"])

    def on_epoch_end(self, epoch=None, logs=None):
        self._log("train", logs, self._step["train"])

    def on_eval_end(self, logs=None):
        self._step["eval"] += 1
        self._log("eval", logs, self._step["eval"])

    def on_train_end(self, logs=None):
        for w in self._writers.values():
            w.close()
        self._writers.clear()


class WandbCallback(Callback):
    """Weights & Biases callback (reference hapi/callbacks.py
    WandbCallback). Requires the optional wandb package."""

    def __init__(self, project=None, entity=None, name=None, dir=None,
                 mode=None, job_type=None, **kwargs):
        super().__init__()
        try:
            import wandb
            self.wandb = wandb
        except ImportError as e:
            raise RuntimeError(
                "You want to use wandb which is not installed yet; install "
                "it with `pip install wandb`") from e
        self._run = None
        self._kwargs = dict(project=project, entity=entity, name=name,
                            dir=dir, mode=mode, job_type=job_type, **kwargs)

    @property
    def run(self):
        if self._run is None:
            if self.wandb.run is not None:
                self._run = self.wandb.run
            else:
                self._run = self.wandb.init(
                    **{k: v for k, v in self._kwargs.items()
                       if v is not None})
        return self._run

    def _log(self, prefix, logs, step=None):
        logs = logs or {}
        payload = {f"{prefix}/{k}": v for k, v in logs.items()
                   if isinstance(v, (int, float))}
        if payload:
            self.run.log(payload, step=step)

    def on_train_begin(self, logs=None):
        _ = self.run

    def on_epoch_end(self, epoch=None, logs=None):
        self._log("train", logs)

    def on_eval_end(self, logs=None):
        self._log("eval", logs)

    def on_train_end(self, logs=None):
        if self._run is not None:
            self._run.finish()
            self._run = None


__all__ += ["VisualDL", "WandbCallback"]
