"""`paddle.hub` (reference: python/paddle/hub.py) — load models/entry points
from a `hubconf.py`. The TPU build supports the `local` source fully; remote
sources (`github`/`gitee`) require network access and raise a clear error in
the zero-egress environment unless the repo is already cached."""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ['list', 'help', 'load']

_HUBCONF = 'hubconf.py'
HUB_DIR = os.environ.get(
    'PADDLE_TPU_HUB_DIR',
    os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu', 'hub'))


def _cache_dir_for(repo_dir: str) -> str:
    # "owner/repo[:branch]" → cached checkout path
    name = repo_dir.replace('/', '_').replace(':', '_')
    return os.path.join(HUB_DIR, name)


def _resolve(repo_dir: str, source: str) -> str:
    source = source.lower()
    if source not in ('github', 'gitee', 'local'):
        raise ValueError(
            f"Unknown source: {source}. Valid: 'github', 'gitee', 'local'.")
    if source == 'local':
        path = os.path.expanduser(repo_dir)
    else:
        path = _cache_dir_for(repo_dir)
        if not os.path.isdir(path):
            raise RuntimeError(
                f"hub source '{source}' needs network access to fetch "
                f"{repo_dir!r}; this environment has no egress. Pre-populate "
                f"{path} with the repo checkout, or use source='local'.")
    if not os.path.isfile(os.path.join(path, _HUBCONF)):
        raise FileNotFoundError(f"no {_HUBCONF} found under {path}")
    return path


def _import_hubconf(path: str):
    file = os.path.join(path, _HUBCONF)
    spec = importlib.util.spec_from_file_location('paddle_tpu_hubconf', file)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, path)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(path)
    deps = getattr(mod, 'dependencies', [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(f'hubconf dependencies missing: {missing}')
    return mod


def list(repo_dir, source='github', force_reload=False):  # noqa: A001
    """Entrypoint names (public callables) defined by the repo's hubconf."""
    mod = _import_hubconf(_resolve(repo_dir, source))
    return [n for n, v in vars(mod).items()
            if callable(v) and not n.startswith('_')]


def help(repo_dir, model, source='github', force_reload=False):  # noqa: A002
    """Docstring of one entrypoint."""
    mod = _import_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f'no entrypoint named {model!r} in {_HUBCONF}')
    return fn.__doc__


def load(repo_dir, model, source='github', force_reload=False, **kwargs):
    """Call the entrypoint and return its result (usually a Layer)."""
    mod = _import_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f'no entrypoint named {model!r} in {_HUBCONF}')
    return fn(**kwargs)
