"""NaN/Inf sentinel: numeric blow-up detection without per-step host syncs.

Per-step cost is one device-side ``isfinite().all()`` folded into a running
device scalar — no transfer, no dispatch stall (the pattern the TS00x
trace-safety rules require: the host pull happens only on the check
cadence, one sync per ``check_every`` steps, batched over the whole
window).

On a bad window the sentinel cooperates with ``amp.GradScaler``: steps the
scaler already skipped (its ``found_inf`` bookkeeping) never polluted the
parameters, so the first response is to *skip* — reset the window and let
dynamic loss scaling back off. Only after ``max_consecutive`` consecutive
bad windows does it escalate: rewind to the last good checkpoint
(``action="rewind"``, needs a :class:`CheckpointManager`), or raise
:class:`NumericsError` (``action="raise"``).

Telemetry: ``paddle_tpu_resilience_nan_events_total`` (bad windows),
``_nan_skips_total``, ``_nan_rewinds_total``.
"""

from __future__ import annotations

from ..observability import counter as _obs_counter
from ..observability import flight as _flight

__all__ = ["NaNSentinel", "NumericsError"]

_OBS_EVENTS = _obs_counter(
    "paddle_tpu_resilience_nan_events_total",
    "sentinel check windows containing a non-finite loss/grad")
_OBS_SKIPS = _obs_counter(
    "paddle_tpu_resilience_nan_skips_total",
    "bad windows absorbed without rewind (scaler-handled or under patience)")
_OBS_REWINDS = _obs_counter(
    "paddle_tpu_resilience_nan_rewinds_total",
    "rewinds to the last good checkpoint after max_consecutive bad windows")


class NumericsError(RuntimeError):
    """Raised by NaNSentinel(action="raise") after max_consecutive
    consecutive bad check windows."""


class NaNSentinel:
    """Watch loss (and optionally grad) finiteness on a cadence.

    ::

        sentinel = NaNSentinel(check_every=25, max_consecutive=3,
                               manager=mgr, scaler=scaler)
        for step in range(start, total):
            loss = train_step(...)
            sentinel.observe(loss)
            if sentinel.check(step, model=model, optimizer=opt) == "rewind":
                step_resume = mgr.latest_step()  # loop may rewind its cursor

    ``observe`` is device-only; ``check`` returns None off-cadence (no host
    work) and otherwise one of None (window clean), "skip", "rewind".
    """

    def __init__(self, check_every: int = 25, max_consecutive: int = 3,
                 manager=None, scaler=None, action: str = "rewind"):
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        if action not in ("rewind", "skip", "raise"):
            raise ValueError(f"unknown action {action!r}")
        if action == "rewind" and manager is None:
            raise ValueError('action="rewind" needs a CheckpointManager')
        self.check_every = check_every
        self.max_consecutive = max_consecutive
        self.manager = manager
        self.scaler = scaler
        self.action = action
        self._ok_accum = None        # device scalar: AND of window finiteness
        self._bad_windows = 0
        self._scaler_inf_seen = self._scaler_inf_total()
        #: step of the checkpoint the last "rewind" actually restored — the
        #: loop must reset its cursor to THIS, not to manager.latest_step()
        #: (restore() may have fallen back past a corrupt newer checkpoint)
        self.restored_step: int | None = None

    def _scaler_inf_total(self) -> int:
        return getattr(self.scaler, "inf_steps_total", 0) \
            if self.scaler is not None else 0

    # -- hot path (device only) ----------------------------------------------

    def observe(self, loss, optimizer=None) -> None:
        """Fold this step's finiteness into the window accumulator —
        device-side elementwise ops only, safe to call every step."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        arr = loss._data if isinstance(loss, Tensor) else jnp.asarray(loss)
        fin = jnp.all(jnp.isfinite(arr))
        if optimizer is not None:
            for p in optimizer._parameter_list:
                if p._grad is not None:
                    fin = jnp.logical_and(
                        fin, jnp.all(jnp.isfinite(p._grad._data)))
        self._ok_accum = fin if self._ok_accum is None \
            else jnp.logical_and(self._ok_accum, fin)

    # -- cadence path (one host sync per window) -----------------------------

    def should_check(self, step: int) -> bool:
        return (step + 1) % self.check_every == 0

    def check(self, step: int, model=None, optimizer=None,
              lr_scheduler=None, dataloader=None, health=None) -> str | None:
        """Off-cadence: returns None untouched. On cadence: one host pull of
        the window accumulator; classify the window and act. ``health`` (a
        HealthMonitor) is forwarded to ``manager.restore`` on rewind so its
        accumulators are reset to the restored step."""
        if not self.should_check(step) or self._ok_accum is None:
            return None
        ok = bool(self._ok_accum)   # the single batched host sync
        self._ok_accum = None
        if ok:
            self._bad_windows = 0
            self._scaler_inf_seen = self._scaler_inf_total()
            return None
        _OBS_EVENTS.inc()
        self._bad_windows += 1
        _flight.record("nan_window", step=int(step),
                       bad_windows=self._bad_windows,
                       window=self.check_every)
        # scaler cooperation: if dynamic loss scaling caught (and skipped)
        # those steps, parameters are clean — absorb the window
        scaler_total = self._scaler_inf_total()
        scaler_handled = scaler_total > self._scaler_inf_seen
        self._scaler_inf_seen = scaler_total
        if self._bad_windows < self.max_consecutive or \
                (scaler_handled and self._bad_windows < 2 * self.max_consecutive):
            _OBS_SKIPS.inc()
            _flight.record("nan_skip", step=int(step),
                           scaler_handled=scaler_handled)
            return "skip"
        self._bad_windows = 0
        if self.action == "raise":
            _flight.record("nan_raise", step=int(step))
            _flight.dump(reason="nan_raise", step=int(step),
                         dump_dir=getattr(self.manager, "root", None))
            raise NumericsError(
                f"non-finite loss/grad persisted for {self.max_consecutive} "
                f"consecutive check windows (step {step})")
        if self.action == "skip":
            _OBS_SKIPS.inc()
            _flight.record("nan_skip", step=int(step),
                           scaler_handled=scaler_handled)
            return "skip"
        restored = self.manager.restore(model=model, optimizer=optimizer,
                                        scaler=self.scaler,
                                        lr_scheduler=lr_scheduler,
                                        dataloader=dataloader,
                                        health=health)
        if restored is None:
            # rewind exhaustion: the run is about to die — dump the tape
            _flight.record("nan_raise", step=int(step), no_checkpoint=True)
            _flight.dump(reason="nan_rewind_exhausted", step=int(step),
                         dump_dir=self.manager.root)
            raise NumericsError(
                f"non-finite loss/grad at step {step} and no checkpoint to "
                f"rewind to")
        self.restored_step = restored
        _OBS_REWINDS.inc()
        # near-death forensics: the run survives via rewind, but the tape
        # up to the blow-up is exactly what a postmortem needs — snapshot
        # it now, before replay overwrites the ring
        _flight.record("nan_rewind", step=int(step),
                       restored_step=int(restored))
        _flight.dump(reason="nan_rewind", step=int(step),
                     dump_dir=self.manager.root)
        return "rewind"
