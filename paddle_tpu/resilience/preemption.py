"""Preemption-safe shutdown: SIGTERM/SIGINT → drain, checkpoint, exit.

TPU slices make preemption the common case (GSPMD-era schedulers reclaim
slices routinely), so termination is part of the training contract, not an
error path. The handler is *cooperative*: the signal callback only records
the request (safe in any thread/context), and the training loop surfaces it
at the next step boundary via :meth:`PreemptionHandler.maybe_exit`, which

1. drains the in-flight async checkpoint save,
2. writes a final blocking checkpoint at the current step,
3. raises ``SystemExit`` with a source-derived status: 143 (128+SIGTERM,
   the conventional "killed by TERM" code schedulers relaunch) for
   sigterm/elastic/manual, 130 (128+SIGINT) for an operator's Ctrl-C,
   or the explicit ``exit_code`` override.

``attach_elastic`` registers the same request as an
``ElasticManager`` pre-hook, so an ``ElasticStatus.RESTART`` scale event
drains and checkpoints through the identical path before the scheduler
relaunches the job.

Telemetry: ``paddle_tpu_resilience_preemptions_total`` {source},
``paddle_tpu_resilience_drain_seconds``.
"""

from __future__ import annotations

import signal
import threading
import time

from ..observability import counter as _obs_counter, histogram as _obs_histogram
from ..observability import flight as _flight

__all__ = ["PreemptionHandler", "TrainingPreempted"]

_OBS_PREEMPTIONS = _obs_counter(
    "paddle_tpu_resilience_preemptions_total",
    "preemption requests by source (sigterm|sigint|elastic|manual)")
_OBS_DRAIN_SECONDS = _obs_histogram(
    "paddle_tpu_resilience_drain_seconds",
    "seconds spent draining async saves + writing the final checkpoint")


class TrainingPreempted(SystemExit):
    """SystemExit subclass raised at the step boundary after the final
    checkpoint committed; ``code`` is the scheduler-relaunchable status."""


class PreemptionHandler:
    """Cooperative SIGTERM/SIGINT (and elastic-restart) checkpoint-and-exit.

    ::

        handler = PreemptionHandler(mgr).install()
        try:
            for step in range(start, total):
                ...
                handler.maybe_exit(step + 1, model=model, optimizer=opt)
        finally:
            handler.uninstall()

    Also usable as a context manager (``with PreemptionHandler(mgr) as h:``).
    """

    def __init__(self, manager=None, exit_code: int | None = None,
                 signals=(signal.SIGTERM, signal.SIGINT),
                 drain_timeout_s: float = 120.0):
        """exit_code=None derives the status from the preemption source —
        128+TERM=143 (scheduler-relaunchable) for sigterm/elastic/manual,
        128+INT=130 for an operator's Ctrl-C, which wrappers must NOT
        auto-relaunch. An explicit int overrides both.
        ``drain_timeout_s`` bounds the async-save drain in
        :meth:`maybe_exit` (a loud RuntimeWarning on expiry)."""
        self.manager = manager
        self.exit_code = None if exit_code is None else int(exit_code)
        self.signals = tuple(signals)
        self.drain_timeout_s = float(drain_timeout_s)
        self._preempted = threading.Event()
        self._source: str | None = None
        self._counted = False    # metric flushed (deferred out of signal ctx)
        # guards the _counted check-then-set: an elastic-hook thread and
        # the training thread may flush concurrently. NEVER taken in
        # signal context (_on_signal goes through _mark only)
        self._metric_lock = threading.Lock()
        self._prev_handlers: dict = {}
        self._installed = False

    # -- signal plumbing ----------------------------------------------------

    def install(self) -> "PreemptionHandler":
        """Register the signal handlers (main thread only, per the signal
        module's contract); idempotent."""
        if not self._installed:
            for sig in self.signals:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            for sig, prev in self._prev_handlers.items():
                signal.signal(sig, prev)
            self._prev_handlers.clear()
            self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _on_signal(self, signum, frame):
        # async-signal context: flag + flight only (both lock-free by
        # construction — CS102). The metric counter takes the registry
        # lock, so it is DEFERRED to the step boundary (maybe_exit); a
        # signal landing while the main thread holds that very lock
        # would otherwise deadlock the process.
        self._mark("sigint" if signum == signal.SIGINT else "sigterm")

    def _mark(self, source: str) -> None:
        """Signal-safe core of a preemption request: a plain attribute
        write, an Event.set, and a flight event. First source wins."""
        if not self._preempted.is_set():
            self._source = source
            self._preempted.set()
            _flight.record("preempt", source=source)

    def request_preemption(self, source: str = "manual") -> None:
        """Mark the run preempted (thread-safe; first source wins).
        Thread-context callers (elastic hooks, manual) — signal handlers
        go through :meth:`_mark` and flush the metric later."""
        self._mark(source)
        self._flush_metric()

    def _flush_metric(self) -> None:
        if not self._preempted.is_set():
            return
        with self._metric_lock:
            if self._counted:
                return
            self._counted = True
        _OBS_PREEMPTIONS.inc(source=self._source or "unknown")

    @property
    def preempted(self) -> bool:
        return self._preempted.is_set()

    @property
    def source(self) -> str | None:
        return self._source

    def attach_elastic(self, elastic_manager) -> "PreemptionHandler":
        """Route ElasticStatus.RESTART through this handler: the elastic
        pre-hook requests preemption, the training loop drains + checkpoints
        + exits for the scheduler to relaunch at the new scale."""
        elastic_manager.register_pre_hook(
            lambda: self.request_preemption("elastic"))
        return self

    # -- step-boundary hook --------------------------------------------------

    def maybe_exit(self, step: int, model=None, optimizer=None, scaler=None,
                   lr_scheduler=None, dataloader=None, extra=None) -> None:
        """No-op until preempted; then drain, write the final checkpoint at
        `step`, and raise TrainingPreempted(exit_code)."""
        if not self._preempted.is_set():
            return
        self._flush_metric()   # the counter deferred out of signal context
        t0 = time.perf_counter()
        if self.manager is not None:
            # drain the in-flight async save — BOUNDED: a wedged save
            # thread must not turn preemption into a hang past the
            # scheduler's kill grace period
            if not self.manager.wait(self.drain_timeout_s):
                import warnings
                warnings.warn(
                    f"async checkpoint save did not drain within "
                    f"{self.drain_timeout_s}s of preemption; attempting "
                    f"the final checkpoint anyway (it may still block if "
                    f"the stuck commit holds the checkpoint io lock)",
                    RuntimeWarning, stacklevel=2)
            # wait_timeout=0.0: the bounded drain above already ran —
            # save() must not re-join the wedged thread without a bound
            self.manager.save(step, model=model, optimizer=optimizer,
                              scaler=scaler, lr_scheduler=lr_scheduler,
                              dataloader=dataloader, extra=extra,
                              blocking=True, wait_timeout=0.0)
        try:
            # the live telemetry server must not outlive the run: close
            # the socket and join the acceptor thread as part of the drain
            # (scrapers see connection-refused, not a zombie endpoint)
            from ..observability.continuous import shutdown_server
            shutdown_server()
        except Exception:
            pass
        _OBS_DRAIN_SECONDS.observe(time.perf_counter() - t0)
        code = self.exit_code
        if code is None:
            code = 130 if self._source == "sigint" else 143
        # the black box: final checkpoint is committed, so the tape up to
        # here IS the full story of this incarnation — dump it next to the
        # checkpoints before exiting
        _flight.record("preempt_exit", step=int(step), source=self._source,
                       code=code)
        _flight.dump(reason=f"preempted_{self._source or 'unknown'}",
                     step=int(step),
                     dump_dir=getattr(self.manager, "root", None))
        raise TrainingPreempted(code)
