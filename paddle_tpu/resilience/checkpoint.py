"""Atomic, async, self-verifying training checkpoints.

A checkpoint is two files in the manager's root directory::

    ckpt-0000000012.pkl    pickled payload (tensors packed as numpy arrays,
                           the framework/io.py serialization format)
    ckpt-0000000012.json   manifest: {"step", "sha256", "bytes", "time",
                           "format_version", "keys"}

Commit protocol (crash-safe in any prefix):

1. payload is snapshotted to HOST numpy at ``save()`` call time — an async
   save never races the training loop mutating device state;
2. bytes go to ``<name>.pkl.tmp-<pid>``, are flushed and ``fsync``\\ ed,
   then ``os.replace``\\ d over the final ``.pkl`` name (atomic on POSIX);
3. the manifest (carrying the payload's sha256) is written the same way,
   LAST — a ``.pkl`` without its manifest is invisible to ``restore()``,
   and a manifest whose hash mismatches its payload marks it corrupt.

``restore()`` walks manifests newest-first and falls back across missing /
truncated / hash-mismatched checkpoints until one verifies, so a crash at
any byte of a save can never cost more than that one save. Retention
(``keep_n``) deletes oldest-first and only after a newer checkpoint has
fully committed.

Telemetry (paddle_tpu.observability): ``paddle_tpu_resilience_saves_total``
{status=ok|error}, ``_save_seconds``, ``_restores_total``,
``_restore_fallbacks_total``, ``_corrupt_checkpoints_total``,
``_last_checkpoint_step`` gauge.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import threading
import time

from ..analysis.concurrency import tsan as _tsan
from ..framework.io import _fsync_dir
from ..observability import (counter as _obs_counter, gauge as _obs_gauge,
                             histogram as _obs_histogram)
from ..observability import flight as _flight
from . import faults as _faults

__all__ = ["CheckpointManager", "CheckpointNotFoundError"]

FORMAT_VERSION = 1

_OBS_SAVES = _obs_counter(
    "paddle_tpu_resilience_saves_total",
    "checkpoint save attempts by terminal status (ok|error)")
_OBS_SAVE_SECONDS = _obs_histogram(
    "paddle_tpu_resilience_save_seconds",
    "wall seconds per checkpoint commit (serialize + write + fsync)")
_OBS_RESTORES = _obs_counter(
    "paddle_tpu_resilience_restores_total",
    "successful CheckpointManager.restore() calls")
_OBS_FALLBACKS = _obs_counter(
    "paddle_tpu_resilience_restore_fallbacks_total",
    "restore() skips over a newer unusable checkpoint to an older good one")
_OBS_CORRUPT = _obs_counter(
    "paddle_tpu_resilience_corrupt_checkpoints_total",
    "checkpoints rejected at restore time (missing payload, bad hash, "
    "undecodable)")
_OBS_LAST_STEP = _obs_gauge(
    "paddle_tpu_resilience_last_checkpoint_step",
    "step of the most recently committed checkpoint")


class CheckpointNotFoundError(FileNotFoundError):
    """restore(required=True) found no usable checkpoint."""


def _atomic_write(path: str, data: bytes, fault_site: str | None = None):
    """tmp + write + fsync + os.replace; the tmp file is removed on any
    failure so a crashed write leaves nothing a reader could mistake for a
    checkpoint."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            if fault_site is not None:
                # fire mid-write: half the payload lands in the tmp file
                # before the injected error, proving partial writes stay
                # invisible
                f.write(data[:len(data) // 2])
                _faults.on_save_write(path)
                f.write(data[len(data) // 2:])
            else:
                f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class CheckpointManager:
    """Persist and recover full training state ({model, optimizer, scaler,
    lr_scheduler, rng, step, extra}) with atomic commits, rolling retention
    and optional background saves.

    ::

        mgr = CheckpointManager("ckpts", keep_n=3, async_save=True)
        start = mgr.restore(model=model, optimizer=opt) or 0
        for step in range(start, total):
            ...
            if (step + 1) % save_every == 0:
                mgr.save(step + 1, model=model, optimizer=opt)
        mgr.wait()
    """

    def __init__(self, root: str, keep_n: int = 3, async_save: bool = False,
                 prefix: str = "ckpt"):
        if keep_n < 1:
            raise ValueError("keep_n must be >= 1")
        if not re.fullmatch(r"[A-Za-z0-9_.-]+", prefix):
            raise ValueError(f"prefix {prefix!r} must be filename-safe")
        self.root = os.fspath(root)
        self.keep_n = keep_n
        self.async_save = async_save
        self.prefix = prefix
        os.makedirs(self.root, exist_ok=True)
        # a CheckpointManager marks a managed training run: point the
        # flight recorder's DEFAULT dump dir at the checkpoint dir (for the
        # excepthook path, which has no owning manager; last-constructed
        # manager wins) and arm the unhandled-exception hook (idempotent,
        # chained). Manager-owned death paths (save errors, NaN rewinds,
        # preemption) pass their own root explicitly instead.
        _flight.set_dump_dir(self.root)
        _flight.install_excepthook()
        # serializes commits + retention; also guards _last_error, which
        # the background save thread writes and caller threads read
        self._io_lock = _tsan.lock("resilience.CheckpointManager.io")
        self._inflight: threading.Thread | None = None
        self._last_error: BaseException | None = None
        self._manifest_re = re.compile(
            re.escape(prefix) + r"-(\d{10})\.json$")

    # -- naming --------------------------------------------------------------

    def _payload_path(self, step: int) -> str:
        return os.path.join(self.root, f"{self.prefix}-{step:010d}.pkl")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.root, f"{self.prefix}-{step:010d}.json")

    def all_steps(self) -> list[int]:
        """Steps with a committed manifest, ascending (manifest presence,
        not payload validity — restore() verifies content)."""
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for n in names:
            m = self._manifest_re.fullmatch(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    @property
    def last_error(self) -> BaseException | None:
        """The exception that killed the most recent (async) save, if any."""
        with self._io_lock:
            return self._last_error

    # -- save ----------------------------------------------------------------

    def save(self, step: int, model=None, optimizer=None, scaler=None,
             lr_scheduler=None, dataloader=None, extra=None,
             blocking: bool | None = None,
             wait_timeout: float | None = None):
        """Snapshot state now; commit synchronously or in the background.

        Any component may be omitted. RNG state (global generator + named
        tracker streams) is always captured. ``dataloader`` is anything
        exposing the checkpointable-iterator contract (``state_dict()`` —
        a ``DataLoader(seed=...)`` or the ``DevicePrefetcher`` wrapping
        one); its cursor is captured at call time like every other
        component, so the restored stream resumes at exactly the batch the
        training loop would have consumed next. Returns the background
        thread when committing asynchronously, else None.

        ``wait_timeout`` bounds the drain of a previous in-flight async
        save (default: block until drained). The preemption path passes
        0.0 — it already waited its own bounded drain, and a wedged
        commit thread must not block the final checkpoint (whose file
        writes are still serialized against it by the io lock).
        """
        payload = self._snapshot(step, model, optimizer, scaler,
                                 lr_scheduler, dataloader, extra)
        sync = not self.async_save if blocking is None else blocking
        drained = self.wait(wait_timeout)  # ≤1 in flight; bounds memory
        if sync:
            self._commit(step, payload)
            return None
        # a bounded wait that expired leaves the previous commit thread
        # alive: CHAIN behind it instead of overwriting _inflight (which
        # would run two commits at once and make wait() lie about being
        # drained)
        prev = None if drained else self._inflight

        def _run():
            if prev is not None:
                prev.join()
            self._commit_guarded(step, payload)

        th = threading.Thread(target=_run, daemon=True,
                              name=f"ckpt-save-{step}")
        self._inflight = th
        th.start()
        return th

    def _snapshot(self, step, model, optimizer, scaler, lr_scheduler,
                  dataloader, extra):
        """Pack every component to host-side plain objects at call time."""
        from ..core.generator import get_rng_state, get_rng_state_tracker
        from ..framework.io import _pack
        payload: dict = {"step": int(step),
                         "rng": get_rng_state(),
                         "rng_tracker":
                             get_rng_state_tracker().get_states_tracker()}
        if dataloader is not None:
            payload["data"] = dict(dataloader.state_dict())
        if model is not None:
            sd = model.state_dict() if hasattr(model, "state_dict") else model
            payload["model"] = _pack(sd)
        if optimizer is not None:
            sd = optimizer.state_dict() \
                if hasattr(optimizer, "state_dict") else optimizer
            payload["optimizer"] = _pack(sd)
        if scaler is not None:
            payload["scaler"] = scaler.state_dict()
        if lr_scheduler is not None:
            payload["lr_scheduler"] = lr_scheduler.state_dict()
        if extra is not None:
            payload["extra"] = _pack(extra)
        return payload

    def _commit_guarded(self, step, payload):
        try:
            self._commit(step, payload)
        except BaseException as e:  # background thread: record, don't kill
            with self._io_lock:
                self._last_error = e

    def _commit(self, step, payload):
        t0 = time.perf_counter()
        try:
            blob = pickle.dumps(payload, protocol=4)
            digest = hashlib.sha256(blob).hexdigest()
            manifest = {"step": int(step), "sha256": digest,
                        "bytes": len(blob), "time": time.time(),
                        "format_version": FORMAT_VERSION,
                        "keys": sorted(k for k in payload
                                       if k not in ("step",))}
            with self._io_lock:
                _atomic_write(self._payload_path(step), blob,
                              fault_site="ckpt.write")
                _atomic_write(self._manifest_path(step),
                              json.dumps(manifest).encode())
                self._retain_locked()
        except BaseException as e:
            _OBS_SAVES.inc(status="error")
            # a failed commit is abnormal-death territory (the training
            # loop may be about to crash on it): record AND dump now,
            # while the events leading here still exist
            _flight.record("checkpoint_save", step=int(step), status="error",
                           error=repr(e)[:200])
            _flight.dump(reason="checkpoint_save_error", step=int(step),
                         dump_dir=self.root)
            raise
        with self._io_lock:
            self._last_error = None
        _OBS_SAVES.inc(status="ok")
        _OBS_SAVE_SECONDS.observe(time.perf_counter() - t0)
        _OBS_LAST_STEP.set(step)
        _flight.record("checkpoint_save", step=int(step), status="ok",
                       bytes=len(blob),
                       seconds=round(time.perf_counter() - t0, 4))

    def _retain_locked(self):
        for step in self.all_steps()[:-self.keep_n]:
            for p in (self._manifest_path(step), self._payload_path(step)):
                # manifest first: a crash between the two unlinks leaves an
                # orphan payload (ignored), never a manifest without payload
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def wait(self, timeout: float | None = None) -> bool:
        """Drain the in-flight async save, if any. Returns True when no
        save remains in flight afterwards (False = the timeout expired
        with the commit thread still running — the preemption drain
        turns that into a loud RuntimeWarning)."""
        th = self._inflight
        if th is not None:
            th.join(timeout)
            if th.is_alive():
                return False
            self._inflight = None
        return True

    # -- restore -------------------------------------------------------------

    def _verify(self, step: int) -> dict | None:
        """Manifest + payload of `step` if internally consistent."""
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
            with open(self._payload_path(step), "rb") as f:
                blob = f.read()
        except (OSError, ValueError):
            return None
        if manifest.get("format_version") != FORMAT_VERSION:
            return None
        if len(blob) != manifest.get("bytes") or \
                hashlib.sha256(blob).hexdigest() != manifest.get("sha256"):
            return None
        try:
            return pickle.loads(blob)
        except Exception:
            return None

    def restore(self, model=None, optimizer=None, scaler=None,
                lr_scheduler=None, dataloader=None, step: int | None = None,
                required: bool = False, health=None):
        """Load the newest good checkpoint (or exactly `step`) into the
        given components, in place. ``dataloader`` receives the saved
        iterator cursor via ``load_state_dict`` (exactly-once resume: the
        batches that were speculative at save time are replayed, nothing
        is skipped). ``health`` (a HealthMonitor) is notified via
        ``on_restore`` so its window accumulators and EWMA baselines drop
        the poisoned tail. Returns the restored step, or None when no
        usable checkpoint exists (raises CheckpointNotFoundError when
        ``required``). Corrupt or partial checkpoints are counted, skipped,
        and never applied."""
        self.wait()  # an async save may still be committing
        candidates = [step] if step is not None \
            else list(reversed(self.all_steps()))
        fallbacks = 0
        for st in candidates:
            payload = self._verify(st)
            if payload is None:
                _OBS_CORRUPT.inc()
                fallbacks += 1
                continue
            self._apply(payload, model, optimizer, scaler, lr_scheduler,
                        dataloader)
            _OBS_RESTORES.inc()
            if fallbacks:
                _OBS_FALLBACKS.inc(fallbacks)
            _flight.record("checkpoint_restore", step=int(payload["step"]),
                           fallbacks=fallbacks)
            if health is not None:
                health.on_restore(int(payload["step"]))
            return payload["step"]
        if required:
            raise CheckpointNotFoundError(
                f"no usable checkpoint under {self.root!r} "
                f"(examined {len(candidates)})")
        return None

    def _apply(self, payload, model, optimizer, scaler, lr_scheduler,
               dataloader=None):
        from ..core.generator import (set_rng_state, get_rng_state_tracker)
        from ..framework.io import _unpack
        if model is not None and "model" in payload:
            model.set_state_dict(_unpack(payload["model"]))
        if optimizer is not None and "optimizer" in payload:
            optimizer.set_state_dict(_unpack(payload["optimizer"]))
        if scaler is not None and "scaler" in payload:
            scaler.load_state_dict(payload["scaler"])
        if lr_scheduler is not None and "lr_scheduler" in payload:
            lr_scheduler.set_state_dict(dict(payload["lr_scheduler"]))
        if dataloader is not None and "data" in payload:
            dataloader.load_state_dict(dict(payload["data"]))
        if "rng" in payload:
            set_rng_state(payload["rng"])
        if payload.get("rng_tracker"):
            get_rng_state_tracker().set_states_tracker(
                payload["rng_tracker"])

    def load_extra(self, step: int | None = None):
        """The "extra" payload of the newest good checkpoint (or `step`),
        unpacked; None when absent."""
        from ..framework.io import _unpack
        candidates = [step] if step is not None \
            else list(reversed(self.all_steps()))
        for st in candidates:
            payload = self._verify(st)
            if payload is not None:
                return _unpack(payload.get("extra"))
        return None
