"""Deterministic fault injection for the fault-tolerant runtime.

Every recovery path in `paddle_tpu.resilience` is provable end-to-end only
if the failure itself is reproducible, so injection is deterministic by
construction: a fault fires at the Nth occurrence of a named site (or at an
exact training step), never by random sampling.

Spec grammar (``PADDLE_TPU_FAULTS`` environment variable or
:func:`install` / :func:`inject`)::

    spec     := clause ("," clause)*
    clause   := kind "@" n [":" param]
    kind     := "save_io" | "nan" | "sigterm" | "worker_slow" | "worker_dead"
              | "data_io" | "loader_stall"
    n        := integer — step number for step-indexed kinds (nan, sigterm),
                1-based occurrence count for event-indexed kinds
                (save_io, worker_slow, worker_dead, data_io, loader_stall)
    param    := float — kind-specific (worker_slow / loader_stall: seconds
                to stall)

Examples::

    PADDLE_TPU_FAULTS="save_io@2"          # 2nd checkpoint write raises IOError
    PADDLE_TPU_FAULTS="nan@5"              # loss becomes NaN at step 5
    PADDLE_TPU_FAULTS="sigterm@7"          # SIGTERM delivered entering step 7
    PADDLE_TPU_FAULTS="worker_slow@3:2.5"  # 3rd worker fetch stalls 2.5 s
    PADDLE_TPU_FAULTS="worker_dead@3"      # 3rd worker fetch hard-exits
    PADDLE_TPU_FAULTS="data_io@2"          # 2nd streaming record read raises
    PADDLE_TPU_FAULTS="loader_stall@4:1.5" # 4th loader batch stalls 1.5 s
    PADDLE_TPU_FAULTS="nan@5,nan@6,sigterm@9"   # clauses compose

Step-indexed clauses are one-shot: after firing at step N they are consumed,
so a recovery path that rewinds and replays step N does not re-fault forever.
Event-indexed clauses count occurrences monotonically and fire exactly at
the Nth.

Hook sites are no-ops when no injector is active (one module-level load +
``None`` test), so framework code keeps them unconditionally. DataLoader
worker processes inherit the spec through the environment (fork and spawn
both), which is how the slow/dead-worker clauses reach the child.
"""

from __future__ import annotations

import os
import signal
import threading
import time

from ..observability import counter as _obs_counter
from ..observability import flight as _flight

__all__ = ["FaultSpec", "FaultInjector", "install", "uninstall", "inject",
           "get_active", "on_save_write", "on_train_step", "on_worker_fetch",
           "on_data_read", "on_loader_next", "InjectedIOError"]

KINDS = ("save_io", "nan", "sigterm", "worker_slow", "worker_dead",
         "data_io", "loader_stall")
_STEP_INDEXED = ("nan", "sigterm")

_OBS_INJECTED = _obs_counter(
    "paddle_tpu_resilience_faults_injected_total",
    "faults fired by the injection harness, by kind")


class InjectedIOError(IOError):
    """IOError raised by a ``save_io`` clause (distinguishable from real
    filesystem failures in logs and tests)."""


class FaultSpec:
    __slots__ = ("kind", "at", "param")

    def __init__(self, kind: str, at: int, param: float | None = None):
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; expected one of {KINDS}")
        self.kind = kind
        self.at = int(at)
        self.param = param

    def __repr__(self):
        p = f":{self.param}" if self.param is not None else ""
        return f"{self.kind}@{self.at}{p}"


def _parse(spec: str) -> list[FaultSpec]:
    out = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if "@" not in clause:
            raise ValueError(
                f"bad fault clause {clause!r}: expected kind@n[:param]")
        kind, _, rest = clause.partition("@")
        n, _, param = rest.partition(":")
        out.append(FaultSpec(kind.strip(), int(n),
                             float(param) if param else None))
    return out


class FaultInjector:
    """Holds parsed clauses plus per-kind occurrence counters.

    Occurrence counters are process-local: the parent counts checkpoint
    writes, each worker process counts its own fetches. Thread-safe — the
    async checkpoint thread and the training thread may both hit sites.
    """

    def __init__(self, clauses: list[FaultSpec]):
        self.clauses = list(clauses)
        self._occurrences: dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultInjector":
        return cls(_parse(spec))

    def _next_occurrence(self, kind: str) -> int:
        with self._lock:
            n = self._occurrences.get(kind, 0) + 1
            self._occurrences[kind] = n
            return n

    def _match_event(self, kind: str) -> FaultSpec | None:
        """Event-indexed match: does the Nth occurrence of `kind` fire?"""
        n = self._next_occurrence(kind)
        for c in self.clauses:
            if c.kind == kind and c.at == n:
                return c
        return None

    def _match_step(self, kind: str, step: int) -> FaultSpec | None:
        """Step-indexed match, one-shot: a recovery path that rewinds and
        REPLAYS the faulted step must not re-trigger the same fault."""
        with self._lock:
            for c in self.clauses:
                if c.kind == kind and c.at == step:
                    self.clauses.remove(c)
                    return c
        return None

    # -- site implementations ------------------------------------------------

    def save_write(self, path: str = "") -> None:
        c = self._match_event("save_io")
        if c is not None:
            _OBS_INJECTED.inc(kind="save_io")
            _flight.record("fault_injected", fault="save_io", at=c.at)
            raise InjectedIOError(
                f"injected IO error during save ({path or 'checkpoint'})")

    def train_step(self, step: int) -> bool:
        """Returns True when the loop must corrupt this step's loss with NaN;
        delivers SIGTERM to this process when a sigterm clause matches."""
        c = self._match_step("sigterm", step)
        if c is not None:
            _OBS_INJECTED.inc(kind="sigterm")
            _flight.record("fault_injected", fault="sigterm", step=step)
            signal.raise_signal(signal.SIGTERM)
        c = self._match_step("nan", step)
        if c is not None:
            _OBS_INJECTED.inc(kind="nan")
            _flight.record("fault_injected", fault="nan", step=step)
            return True
        return False

    def worker_fetch(self) -> None:
        """Inside a DataLoader worker: stall or hard-exit on a matching
        clause (hard exit bypasses Python teardown — the parent must detect
        the dead process, not an exception message)."""
        c = self._match_event("worker_slow")
        if c is not None:
            _OBS_INJECTED.inc(kind="worker_slow")
            _flight.record("fault_injected", fault="worker_slow", at=c.at)
            time.sleep(c.param if c.param is not None else 5.0)
        c = self._match_event("worker_dead")
        if c is not None:
            _OBS_INJECTED.inc(kind="worker_dead")
            # recorded for symmetry, but this lands on the WORKER's ring
            # and dies with os._exit — the durable signal is the consumer
            # side's worker_dead event (WorkerDiedError, exit code 3)
            _flight.record("fault_injected", fault="worker_dead", at=c.at)
            os._exit(3)

    def data_read(self, detail: str = "") -> None:
        """Inside a streaming record read: the Nth read raises an
        InjectedIOError. The sharded reader's bounded retry+backoff is the
        recovery path under test — a transient clause is absorbed, repeated
        clauses exhaust the retry budget and surface DataReadError."""
        c = self._match_event("data_io")
        if c is not None:
            _OBS_INJECTED.inc(kind="data_io")
            _flight.record("fault_injected", fault="data_io", at=c.at)
            raise InjectedIOError(
                f"injected IO error during data read ({detail or 'record'})")

    def loader_next(self) -> None:
        """In the loader's batch-yield path: the Nth batch stalls for
        ``param`` seconds (default 1.0), modelling a slow storage tier the
        wait histogram and prefetch buffer must absorb."""
        c = self._match_event("loader_stall")
        if c is not None:
            _OBS_INJECTED.inc(kind="loader_stall")
            _flight.record("fault_injected", fault="loader_stall", at=c.at)
            time.sleep(c.param if c.param is not None else 1.0)


_active: FaultInjector | None = None
_env_checked = False


def get_active() -> FaultInjector | None:
    """The installed injector, lazily bootstrapped from PADDLE_TPU_FAULTS
    the first time any site is consulted."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get("PADDLE_TPU_FAULTS", "")
        if spec:
            _active = FaultInjector.parse(spec)
    return _active


def install(spec: str) -> FaultInjector:
    """Install an injector process-wide (replaces any active one). A string
    spec is also exported to ``PADDLE_TPU_FAULTS`` so child processes that
    don't inherit this interpreter's memory (spawn-started DataLoader
    workers) bootstrap the same clauses from the environment; fork-started
    children inherit the live injector object directly."""
    global _active, _env_checked
    _env_checked = True
    if isinstance(spec, str):
        _active = FaultInjector.parse(spec)
        os.environ["PADDLE_TPU_FAULTS"] = spec
    else:
        _active = spec
    return _active


def uninstall() -> None:
    global _active, _env_checked
    _active = None
    _env_checked = True
    os.environ.pop("PADDLE_TPU_FAULTS", None)


class inject:
    """Context manager: ``with faults.inject("nan@5"): train()``."""

    def __init__(self, spec: str):
        self._spec = spec
        self._saved = None
        self._saved_env = None

    def __enter__(self) -> FaultInjector:
        global _active
        self._saved = _active
        self._saved_env = os.environ.get("PADDLE_TPU_FAULTS")
        return install(self._spec)

    def __exit__(self, *exc):
        global _active
        _active = self._saved
        if self._saved_env is None:
            os.environ.pop("PADDLE_TPU_FAULTS", None)
        else:
            os.environ["PADDLE_TPU_FAULTS"] = self._saved_env
        return False


# -- hook sites (called unconditionally from framework code) -----------------

def on_save_write(path: str = "") -> None:
    inj = get_active()
    if inj is not None:
        inj.save_write(path)


def on_train_step(step: int) -> bool:
    inj = get_active()
    if inj is not None:
        return inj.train_step(step)
    return False


def on_worker_fetch() -> None:
    inj = get_active()
    if inj is not None:
        inj.worker_fetch()


def on_data_read(detail: str = "") -> None:
    inj = get_active()
    if inj is not None:
        inj.data_read(detail)


def on_loader_next() -> None:
    inj = get_active()
    if inj is not None:
        inj.loader_next()
