"""paddle_tpu.resilience — the fault-tolerant training runtime.

Long multi-host TPU runs are terminated by the scheduler, lose workers, and
blow up numerically as a matter of course; this package makes all three
survivable instead of merely observable (`paddle_tpu.observability`) or
statically predictable (`paddle_tpu.analysis`):

* :class:`CheckpointManager` — atomic (tmp + fsync + rename, sha256-hashed
  manifest) persistence of {model, optimizer, GradScaler, LR scheduler,
  RNG, step}, rolling ``keep_n`` retention, background (async) commits, and
  ``restore()`` that detects corrupt/partial checkpoints and falls back to
  the newest good one.
* :class:`PreemptionHandler` — cooperative SIGTERM/SIGINT (and
  ``ElasticStatus.RESTART``) handling: drain the in-flight save, write a
  final checkpoint, exit with a scheduler-relaunchable code (143).
* :class:`NaNSentinel` — loss/grad finiteness on a cadence via a batched
  device-side reduction (no per-step host sync), skip-or-rewind after K
  consecutive bad windows, cooperating with ``amp.GradScaler``.
* :mod:`~paddle_tpu.resilience.faults` — deterministic fault injection
  (``PADDLE_TPU_FAULTS`` spec or :func:`faults.inject` context manager):
  IO errors mid-save, NaN losses, slow/dead DataLoader workers, SIGTERM at
  step N — the harness the recovery tests and ``tools/chaos_check.py``
  drive every path with.

Every recovery event emits through the observability registry under
``paddle_tpu_resilience_*`` — see docs/resilience.md for the full metric
table, manifest format and fault-spec grammar.
"""

from .checkpoint import CheckpointManager, CheckpointNotFoundError  # noqa: F401
from .preemption import PreemptionHandler, TrainingPreempted  # noqa: F401
from .sentinel import NaNSentinel, NumericsError  # noqa: F401
from . import faults  # noqa: F401
from .faults import FaultInjector, FaultSpec, InjectedIOError  # noqa: F401

__all__ = [
    "CheckpointManager", "CheckpointNotFoundError",
    "PreemptionHandler", "TrainingPreempted",
    "NaNSentinel", "NumericsError",
    "FaultInjector", "FaultSpec", "InjectedIOError", "faults",
]
