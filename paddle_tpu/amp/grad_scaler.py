"""GradScaler (reference: python/paddle/amp/grad_scaler.py:578).

Full dynamic loss-scaling semantics for fp16; with bf16 (the TPU default) the
scaler becomes a transparent no-op exactly like `enable=False`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..observability import counter as _obs_counter

__all__ = ["GradScaler", "AmpScaler"]

_OBS_FOUND_INF = _obs_counter(
    "paddle_tpu_amp_scaler_found_inf_total",
    "unscale_ passes that found non-finite grads (update skipped; the "
    "NaN sentinel treats these windows as scaler-handled)")


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer state (reference tracks OptimizerState per optimizer):
        # _unscaled guards the `unscale_ -> clip -> step` pattern against a
        # second divide-by-scale; _found_inf_per keeps inf detection per
        # optimizer so a clean second optimizer cannot mask an inf in the
        # first one's grads
        self._unscaled: set[int] = set()
        self._found_inf_per: dict[int, bool] = {}
        # monotonic count of inf-detected unscale passes: the resilience
        # NaN sentinel reads this to tell "scaler already skipped those
        # steps" apart from "model state is polluted"
        self._inf_steps_total = 0

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        from ..ops.math import scale as _scale_op
        return _scale_op(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last update()")
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad is None:
                continue
            g = p._grad._data.astype(jnp.float32) * inv
            found = found or bool(jnp.any(~jnp.isfinite(g)))
            p._grad._data = g.astype(p._grad._data.dtype)
        self._found_inf_per[id(optimizer)] = found
        # aggregate is sticky until update() resets it
        self._found_inf = self._found_inf or found
        if found:
            self._inf_steps_total += 1
            _OBS_FOUND_INF.inc()
        self._unscaled.add(id(optimizer))

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled:
            # fused path (optimizer/fused.py): unscale, the found_inf
            # reduction, AND the inf-skipped update run inside the one
            # jitted optimizer dispatch — a single host bool pull per step
            # instead of a per-parameter pull in unscale_. Returns None
            # when the fused path can't take it (fusion off, cold state
            # structure, inside a trace): fall through to the legacy path.
            # p.grad still observes the unscaled grads afterwards — the
            # fused program returns them and step() rewrites the handles,
            # matching unscale_'s in-place contract.
            found = self._try_fused_scale_step(optimizer)
            if found is not None:
                if found:
                    self._inf_steps_total += 1
                    _OBS_FOUND_INF.inc()
                    self._found_inf = True
                return
            self.unscale_(optimizer)
        if not self._found_inf_per.get(id(optimizer), False):
            optimizer.step()
        # this optimizer's scale/inf cycle is complete: drop its marks so the
        # next iteration unscales fresh grads even if update() is never
        # called (update() is only required for dynamic scaling); the
        # aggregate _found_inf survives for update()'s scale adjustment
        self._found_inf = self._found_inf or \
            self._found_inf_per.pop(id(optimizer), False)
        self._unscaled.discard(id(optimizer))

    def _try_fused_scale_step(self, optimizer):
        """The fused unscale+step hook, ONLY when it cannot bypass behavior
        layered on top of the update (see fused.resolve_scale_hook):
        wrappers with their own step() logic — ASP mask re-application,
        gradient merge, ZeRO offload streaming — take the legacy
        unscale_/step path, which goes through their step() override."""
        from ..optimizer.fused import resolve_scale_hook
        hook = resolve_scale_hook(optimizer)
        if hook is None:
            return None
        return hook(self._scale)

    def update(self):
        self._unscaled.clear()
        found = self._found_inf or any(self._found_inf_per.values())
        self._found_inf_per.clear()
        self._found_inf = False
        if not self._enable or not self._use_dynamic:
            return
        self._found_inf = found
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    @property
    def found_inf(self) -> bool:
        """Non-finite grads seen in the current scale/update cycle."""
        return self._found_inf or any(self._found_inf_per.values())

    @property
    def inf_steps_total(self) -> int:
        """Monotonic count of inf-detected unscale passes over the scaler's
        lifetime (never reset by update())."""
        return self._inf_steps_total

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._use_dynamic

    def get_init_loss_scaling(self) -> float:
        return self._scale

    def set_init_loss_scaling(self, v: float):
        self._scale = float(v)

    def get_incr_ratio(self):
        return self._incr_ratio

    def get_decr_ratio(self):
        return self._decr_ratio

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable,
                "use_dynamic_loss_scaling": self._use_dynamic}

    def load_state_dict(self, d):
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)


AmpScaler = GradScaler
