"""AMP autocast (reference: python/paddle/amp/auto_cast.py:273 amp_guard).

Mirrors the reference's op-granular insertion (eager_amp_auto_cast.h): the
autograd `apply` consults this module's thread-local state and casts floating
inputs per the white/black lists. On TPU the low dtype defaults to bfloat16.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..core import dtype as dtypes
from . import amp_lists

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate",
           "is_auto_cast_enabled", "get_amp_dtype", "amp_state"]


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.white = amp_lists.white_list()
        self.black = amp_lists.black_list()


_state = _AmpState()


def amp_state() -> _AmpState:
    return _state


def is_auto_cast_enabled() -> bool:
    return _state.enabled


def get_amp_dtype() -> str:
    return jnp.dtype(_state.dtype).name


def cast_for_op(name: str, arrays):
    """Called by autograd.apply: cast float arrays per amp policy."""
    if not _state.enabled:
        return arrays
    low = _state.dtype
    if _state.level == "O2":
        # O2: everything low precision except black-listed ops
        target = jnp.float32 if name in _state.black else low
    else:
        if name in _state.white:
            target = low
        elif name in _state.black:
            target = jnp.float32
        else:
            return arrays
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != jnp.dtype(target):
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (_state.enabled, _state.dtype, _state.level, _state.white, _state.black)
    _state.enabled = bool(enable)
    _state.dtype = dtypes.dtype_from_any(dtype).np_dtype
    _state.level = level
    white = amp_lists.white_list()
    black = amp_lists.black_list()
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    _state.white, _state.black = white, black
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level, _state.white,
         _state.black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2 decoration: cast model params to the low dtype, keep master weights
    in the optimizer (reference: python/paddle/amp/auto_cast.py decorate)."""
    from ..nn.layer import Layer
    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        dt = dtypes.dtype_from_any(dtype)
        excluded = set()
        for m in model_list:
            from ..nn.layers.norm import _BatchNormBase, LayerNorm
            for sub in m.sublayers(include_self=True):
                if isinstance(sub, (_BatchNormBase, LayerNorm)):
                    excluded.add(id(sub))
            for sub in m.sublayers(include_self=True):
                if id(sub) in excluded:
                    continue
                for p in sub.parameters(include_sublayers=False):
                    if dtypes.is_floating_point(p.dtype):
                        p._data = p._data.astype(dt.np_dtype)
        if optimizers is not None:
            opts = optimizers if isinstance(optimizers, (list, tuple)) \
                else [optimizers]
            for o in opts:
                o._multi_precision = True
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


amp_decorate = decorate
