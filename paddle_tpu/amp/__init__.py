from .auto_cast import auto_cast, amp_guard, decorate, amp_decorate, is_auto_cast_enabled, get_amp_dtype  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401
from . import amp_lists  # noqa: F401
from .debugging import check_numerics, enable_operator_stats_collection, disable_operator_stats_collection  # noqa: F401
