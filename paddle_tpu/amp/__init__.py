from .auto_cast import auto_cast, amp_guard, decorate, amp_decorate, is_auto_cast_enabled, get_amp_dtype  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401
from . import amp_lists  # noqa: F401
from .debugging import check_numerics, enable_operator_stats_collection, disable_operator_stats_collection  # noqa: F401


def is_float16_supported(device=None):
    """Reference: amp/__init__.py is_float16_supported. fp16 compute is an
    accelerator capability; the CPU fallback path upcasts."""
    if device is not None:
        plat = str(device).split(":")[0]
    else:
        # probe only when needed: jax.devices() initializes the backend
        import jax
        try:
            plat = jax.devices()[0].platform
        except Exception:
            plat = "cpu"
    return plat in ("tpu", "axon", "gpu")


def is_bfloat16_supported(device=None):
    """Reference: amp/__init__.py is_bfloat16_supported. bf16 is native on
    every TPU generation and emulated losslessly by XLA:CPU."""
    return True
