"""AMP op lists (reference: python/paddle/amp/amp_lists.py + the generated
C++ lists in paddle/fluid/eager/api/generated).

O1 ("white") ops run in low precision; "black" ops stay fp32; the rest follow
their inputs. On TPU bf16 is the native low-precision type, so the default
low dtype is bfloat16 (no loss scaling needed).
"""

WHITE_LIST = {
    "matmul", "mm", "bmm", "addmm", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "scaled_dot_product_attention",
}

BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "kl_div", "cumsum",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "instance_norm",
    "norm", "logsumexp", "erfinv", "pow", "divide",
}

EXTRA_BLACK_LIST_O2 = {
    "lookup_table", "lookup_table_v2", "scatter",
}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)
