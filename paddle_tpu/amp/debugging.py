"""AMP debugging utilities (reference: python/paddle/amp/debugging.py)."""

from __future__ import annotations

import contextlib
from collections import Counter
from enum import Enum

import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor

__all__ = ["check_numerics", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker"]

_op_stats: Counter | None = None


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    t = to_tensor(tensor)
    if jnp.issubdtype(t._data.dtype, jnp.floating):
        n_nan = int(jnp.sum(jnp.isnan(t._data)))
        n_inf = int(jnp.sum(jnp.isinf(t._data)))
        if n_nan or n_inf:
            raise FloatingPointError(
                f"numerics check failed for op={op_type!r} var={var_name!r}: "
                f"{n_nan} NaN, {n_inf} Inf")
    return Tensor(jnp.zeros(3, jnp.float32))


def enable_operator_stats_collection():
    global _op_stats
    _op_stats = Counter()


def disable_operator_stats_collection():
    global _op_stats
    stats, _op_stats = _op_stats, None
    if stats:
        print("<------------------------------ op list ------------------------------>")
        for name, count in sorted(stats.items()):
            print(f"  {name:40s} calls={count}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


# set by paddle_tpu.profiler.Profiler.start() to receive op dispatch names
_PROFILER_OP_HOOK = None


def record_op(name: str):
    if _op_stats is not None:
        _op_stats[name] += 1
    if _PROFILER_OP_HOOK is not None:
        _PROFILER_OP_HOOK(name)


class TensorCheckerConfig:
    def __init__(self, enable=False, debug_mode=None, output_dir=None,
                 checked_op_list=None, skipped_op_list=None, debug_step=None,
                 stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir


def enable_tensor_checker(config: TensorCheckerConfig):
    from ..core.flags import set_flags
    if config.enable:
        set_flags({"check_nan_inf": True})


def disable_tensor_checker():
    from ..core.flags import set_flags
    set_flags({"check_nan_inf": False})


class DebugMode(Enum):
    """TensorCheckerConfig modes (reference amp/debugging.py:42)."""

    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    CHECK_ALL_AND_ABORT = 4
    DUMP_ALL = 5


def check_layer_numerics(func):
    """Decorator checking a layer forward's tensor inputs AND output for
    NaN/Inf (reference amp/debugging.py:64)."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                check_numerics(a, op_type=type(self).__name__,
                               var_name=f"input_{i}")
        out = func(self, *args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            if isinstance(o, Tensor):
                check_numerics(o, op_type=type(self).__name__,
                               var_name=f"output_{i}")
        return out

    return wrapper


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Compare two precision-debug dump directories (reference
    amp/debugging.py:574): pairs same-named tensor dumps (e.g. an fp16 run
    vs an fp32 run), writes a csv of max-abs/mean-abs deltas, and returns
    the rows."""
    import csv
    import os

    import numpy as np

    def load_dir(path):
        out = {}
        for fn in sorted(os.listdir(path)):
            full = os.path.join(path, fn)
            if fn.endswith(".npy"):
                out[fn[:-4]] = np.load(full)
            elif fn.endswith((".log", ".txt")):
                # reference-style textual dumps: one "name value..." per line
                with open(full) as f:
                    for line in f:
                        parts = line.split()
                        if len(parts) >= 2:
                            try:
                                out[parts[0]] = np.asarray(
                                    [float(v) for v in parts[1:]])
                            except ValueError:
                                continue
        return out

    a = load_dir(dump_path)
    b = load_dir(another_dump_path)
    rows = []
    for name in sorted(set(a) & set(b)):
        x = np.asarray(a[name], np.float64) * loss_scale
        y = np.asarray(b[name], np.float64)
        if x.shape != y.shape:
            rows.append((name, "shape_mismatch", x.shape, y.shape))
            continue
        diff = np.abs(x - y)
        rows.append((name, "ok", float(diff.max(initial=0.0)),
                     float(diff.mean() if diff.size else 0.0)))
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["tensor", "status", "max_abs_diff", "mean_abs_diff"])
        w.writerows(rows)
    return rows


__all__ += ["DebugMode", "check_layer_numerics", "compare_accuracy"]
