"""AMP debugging utilities (reference: python/paddle/amp/debugging.py)."""

from __future__ import annotations

import contextlib
from collections import Counter

import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor

__all__ = ["check_numerics", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker"]

_op_stats: Counter | None = None


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    t = to_tensor(tensor)
    if jnp.issubdtype(t._data.dtype, jnp.floating):
        n_nan = int(jnp.sum(jnp.isnan(t._data)))
        n_inf = int(jnp.sum(jnp.isinf(t._data)))
        if n_nan or n_inf:
            raise FloatingPointError(
                f"numerics check failed for op={op_type!r} var={var_name!r}: "
                f"{n_nan} NaN, {n_inf} Inf")
    return Tensor(jnp.zeros(3, jnp.float32))


def enable_operator_stats_collection():
    global _op_stats
    _op_stats = Counter()


def disable_operator_stats_collection():
    global _op_stats
    stats, _op_stats = _op_stats, None
    if stats:
        print("<------------------------------ op list ------------------------------>")
        for name, count in sorted(stats.items()):
            print(f"  {name:40s} calls={count}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


# set by paddle_tpu.profiler.Profiler.start() to receive op dispatch names
_PROFILER_OP_HOOK = None


def record_op(name: str):
    if _op_stats is not None:
        _op_stats[name] += 1
    if _PROFILER_OP_HOOK is not None:
        _PROFILER_OP_HOOK(name)


class TensorCheckerConfig:
    def __init__(self, enable=False, debug_mode=None, output_dir=None,
                 checked_op_list=None, skipped_op_list=None, debug_step=None,
                 stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir


def enable_tensor_checker(config: TensorCheckerConfig):
    from ..core.flags import set_flags
    if config.enable:
        set_flags({"check_nan_inf": True})


def disable_tensor_checker():
    from ..core.flags import set_flags
    set_flags({"check_nan_inf": False})
