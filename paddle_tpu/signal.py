"""`paddle.signal` — STFT family (reference: python/paddle/signal.py, kernels
paddle/phi/kernels/*/frame_kernel.* / stft via fft). TPU-native: framing is a
gather, STFT is frame+window+rfft (XLA FFT HLO), inverse is a scatter-add
overlap-add — all jit-friendly static-shape code."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.tensor import Tensor, as_tensor
from .autograd.function import apply

__all__ = ['stft', 'istft']


def frame(x, frame_length, hop_length, axis=-1, name=None) -> Tensor:
    """Slice ``x`` into overlapping frames along ``axis`` (reference
    python/paddle/signal.py frame). axis=-1 → (..., frame_length, num_frames);
    axis=0 → (num_frames, frame_length, ...)."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    x = as_tensor(x)
    seq_len = x.shape[axis if axis in (0, -1) else -1]
    if frame_length > seq_len:
        raise ValueError(
            f"frame_length ({frame_length}) > sequence length ({seq_len})")
    num_frames = 1 + (seq_len - frame_length) // hop_length

    def f(a):
        if axis == 0:
            idx = (hop_length * jnp.arange(num_frames)[:, None]
                   + jnp.arange(frame_length)[None, :])
            return a[idx]  # (num_frames, frame_length, ...)
        idx = (jnp.arange(frame_length)[:, None]
               + hop_length * jnp.arange(num_frames)[None, :])
        return jnp.take(a, idx, axis=-1)  # (..., frame_length, num_frames)

    return apply(f, x, name="frame")


def _prep_window(window, win_length, n_fft, dtype):
    if window is None:
        w = jnp.ones((win_length,), dtype)
    else:
        w = as_tensor(window)._data.astype(dtype)
        if w.shape != (win_length,):
            raise ValueError(
                f"window must be 1-D of length win_length ({win_length})")
    if win_length < n_fft:  # center-pad the window to n_fft
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode='reflect', normalized=False, onesided=True, name=None) -> Tensor:
    """Short-time Fourier transform → (..., n_fft//2+1 | n_fft, num_frames)."""
    x = as_tensor(x)
    if x.ndim not in (1, 2):
        raise ValueError(f"stft expects a 1-D or 2-D input, got {x.ndim}-D")
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if jnp.iscomplexobj(x._data) and onesided:
        raise ValueError("onesided must be False for complex inputs")
    real_dtype = jnp.real(x._data).dtype
    w = _prep_window(window, win_length, n_fft, real_dtype)

    def f(a):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        seq_len = a.shape[-1]
        num_frames = 1 + (seq_len - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[:, None]
               + hop_length * jnp.arange(num_frames)[None, :])
        frames = jnp.take(a, idx, axis=-1)  # (..., n_fft, num_frames)
        frames = frames * w[:, None]
        if onesided and not jnp.iscomplexobj(a):
            spec = jnp.fft.rfft(frames, axis=-2)
        else:
            spec = jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, real_dtype))
        return spec

    return apply(f, x, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None) -> Tensor:
    """Inverse STFT via windowed overlap-add with window-envelope
    normalization; input (..., n_freq, num_frames)."""
    x = as_tensor(x)
    if x.ndim not in (2, 3):
        raise ValueError(f"istft expects a 2-D or 3-D input, got {x.ndim}-D")
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = _prep_window(window, win_length, n_fft, jnp.float32)

    n_freq, num_frames = x.shape[-2], x.shape[-1]
    expect = n_fft // 2 + 1 if onesided else n_fft
    if n_freq != expect:
        raise ValueError(f"expected {expect} frequency bins, got {n_freq}")
    out_len = n_fft + hop_length * (num_frames - 1)

    def f(spec):
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                frames = jnp.real(frames)
        frames = frames * w[:, None]

        pos = (hop_length * jnp.arange(num_frames)[None, :]
               + jnp.arange(n_fft)[:, None]).reshape(-1)

        def ola(fr):  # fr: (n_fft, num_frames) → (out_len,)
            return jnp.zeros((out_len,), fr.dtype).at[pos].add(fr.reshape(-1))

        batch = frames.shape[:-2]
        flat = frames.reshape((-1, n_fft, num_frames))
        y = jax.vmap(ola)(flat).reshape((*batch, out_len))
        env = ola((w[:, None] * w[:, None] * jnp.ones((1, num_frames))).astype(jnp.float32))
        y = y / jnp.where(env > 1e-11, env, 1.0)
        if center:
            y = y[..., n_fft // 2: out_len - n_fft // 2]
        if length is not None:
            y = y[..., :length]
        return y

    return apply(f, x, name="istft")
