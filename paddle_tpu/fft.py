"""`paddle.fft` — discrete Fourier transforms (reference: python/paddle/fft.py;
kernels paddle/phi/kernels/*/fft_kernel.*). TPU-native: backed by jnp.fft,
which lowers to XLA's FFT HLO; differentiable through the autograd engine."""

from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor, as_tensor
from .autograd.function import apply

__all__ = [
    'fft', 'ifft', 'rfft', 'irfft', 'hfft', 'ihfft',
    'fft2', 'ifft2', 'rfft2', 'irfft2', 'hfft2', 'ihfft2',
    'fftn', 'ifftn', 'rfftn', 'irfftn', 'hfftn', 'ihfftn',
    'fftfreq', 'rfftfreq', 'fftshift', 'ifftshift',
]

_NORMS = ('forward', 'backward', 'ortho')


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm}. Norm should be forward, backward or ortho")
    return norm


def _wrap1(jfn, x, n, axis, norm, name):
    _check_norm(norm)
    return apply(lambda a: jfn(a, n=n, axis=axis, norm=norm), x,
                 name=name)


def _wrapn(jfn, x, s, axes, norm, name):
    _check_norm(norm)
    return apply(lambda a: jfn(a, s=s, axes=axes, norm=norm), x,
                 name=name)


def fft(x, n=None, axis=-1, norm="backward", name=None) -> Tensor:
    return _wrap1(jnp.fft.fft, x, n, axis, norm, "fft")


def ifft(x, n=None, axis=-1, norm="backward", name=None) -> Tensor:
    return _wrap1(jnp.fft.ifft, x, n, axis, norm, "ifft")


def rfft(x, n=None, axis=-1, norm="backward", name=None) -> Tensor:
    return _wrap1(jnp.fft.rfft, x, n, axis, norm, "rfft")


def irfft(x, n=None, axis=-1, norm="backward", name=None) -> Tensor:
    return _wrap1(jnp.fft.irfft, x, n, axis, norm, "irfft")


def hfft(x, n=None, axis=-1, norm="backward", name=None) -> Tensor:
    return _wrap1(jnp.fft.hfft, x, n, axis, norm, "hfft")


def ihfft(x, n=None, axis=-1, norm="backward", name=None) -> Tensor:
    return _wrap1(jnp.fft.ihfft, x, n, axis, norm, "ihfft")


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.fft2, x, s, axes, norm, "fft2")


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.ifft2, x, s, axes, norm, "ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.rfft2, x, s, axes, norm, "rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.irfft2, x, s, axes, norm, "irfft2")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None) -> Tensor:
    _check_norm(norm)
    return apply(
        lambda a: jnp.fft.hfft(
            jnp.fft.ifftn(a, s=None if s is None else s[:-1],
                          axes=axes[:-1], norm=norm) if len(axes) > 1 else a,
            n=None if s is None else s[-1], axis=axes[-1], norm=norm),
        x, name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None) -> Tensor:
    _check_norm(norm)
    return apply(
        lambda a: jnp.fft.fftn(
            jnp.fft.ihfft(a, n=None if s is None else s[-1],
                          axis=axes[-1], norm=norm),
            s=None if s is None else s[:-1], axes=axes[:-1],
            norm=norm) if len(axes) > 1 else jnp.fft.ihfft(
                a, n=None if s is None else s[-1], axis=axes[-1], norm=norm),
        x, name="ihfft2")


def fftn(x, s=None, axes=None, norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.fftn, x, s, axes, norm, "fftn")


def ifftn(x, s=None, axes=None, norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.ifftn, x, s, axes, norm, "ifftn")


def rfftn(x, s=None, axes=None, norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.rfftn, x, s, axes, norm, "rfftn")


def irfftn(x, s=None, axes=None, norm="backward", name=None) -> Tensor:
    return _wrapn(jnp.fft.irfftn, x, s, axes, norm, "irfftn")


def hfftn(x, s=None, axes=None, norm="backward", name=None) -> Tensor:
    if axes is None:
        axes = tuple(range(as_tensor(x).ndim))
    return hfft2(x, s=s, axes=tuple(axes), norm=norm, name=name)


def ihfftn(x, s=None, axes=None, norm="backward", name=None) -> Tensor:
    if axes is None:
        axes = tuple(range(as_tensor(x).ndim))
    return ihfft2(x, s=s, axes=tuple(axes), norm=norm, name=name)


def fftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None) -> Tensor:
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), x, name="fftshift")


def ifftshift(x, axes=None, name=None) -> Tensor:
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), x, name="ifftshift")
