"""Metrics (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, as_tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        self._name = self.__class__.__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred = as_tensor(pred)._data
        label = as_tensor(label)._data
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label.squeeze(-1)
        if label.ndim == pred.ndim:  # one-hot
            label = jnp.argmax(label, axis=-1)
        idx = jnp.argsort(-pred, axis=-1)[..., : self.maxk]
        correct = (idx == label[..., None]).astype(jnp.float32)
        return Tensor(correct)

    def update(self, correct):
        c = np.asarray(as_tensor(correct)._data)
        c2 = c.reshape(-1, c.shape[-1])
        for i, k in enumerate(self.topk):
            self.total[i] += c2[:, :k].sum()
            self.count[i] += c2.shape[0]
        out = self.total / np.maximum(self.count, 1)
        return out[0] if len(self.topk) == 1 else out

    def accumulate(self):
        out = self.total / np.maximum(self.count, 1)
        return float(out[0]) if len(self.topk) == 1 else out.tolist()

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(as_tensor(preds)._data).reshape(-1)
        l = np.asarray(as_tensor(labels)._data).reshape(-1)
        pred_pos = (p > 0.5).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(as_tensor(preds)._data).reshape(-1)
        l = np.asarray(as_tensor(labels)._data).reshape(-1)
        pred_pos = (p > 0.5).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(Metric):
    """Bucketed streaming AUC (reference: metrics.py Auc + the all-reduced
    distributed variant in framework/fleet/metrics.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(as_tensor(preds)._data)
        l = np.asarray(as_tensor(labels)._data).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        else:
            p = p.reshape(-1)
        bucket = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                         self.num_thresholds)
        np.add.at(self._stat_pos, bucket[l == 1], 1)
        np.add.at(self._stat_neg, bucket[l == 0], 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over descending threshold
        pos = self._stat_pos[::-1]
        neg = self._stat_neg[::-1]
        tp = np.cumsum(pos)
        fp = np.cumsum(neg)
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    x = as_tensor(input)._data
    l = as_tensor(label)._data
    if l.ndim == x.ndim and l.shape[-1] == 1:
        l = l.squeeze(-1)
    idx = jnp.argsort(-x, axis=-1)[..., :k]
    c = jnp.any(idx == l[..., None], axis=-1)
    return Tensor(jnp.mean(c.astype(jnp.float32)))
