"""Evaluation metrics (reference: python/paddle/metric/__init__.py) —
model-QUALITY metrics scored over predictions and labels: `Metric` base
plus Accuracy/Precision/Recall/Auc and the functional `accuracy`.

Not to be confused with `paddle_tpu.observability`, the runtime TELEMETRY
registry (Counters/Gauges/Histograms for recompiles, collective traffic,
dataloader stalls, step latency/MFU). Use this package to score what the
model predicts; use `paddle_tpu.observability` to watch how the system
runs.
"""

from .metrics import (  # noqa: F401
    Metric, Accuracy, Precision, Recall, Auc, accuracy,
)

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]
