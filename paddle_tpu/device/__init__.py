"""`paddle.device` equivalent: device queries, synchronization, memory stats.

Reference: python/paddle/device/ + memory stats (paddle/fluid/memory/stats.h
surfaced as paddle.device.cuda.max_memory_allocated). On TPU, memory stats
come from jax's device memory profile.
"""

from __future__ import annotations

import jax

from ..framework.framework import (  # noqa: F401
    get_device, set_device, device_count, CPUPlace, CUDAPlace, TPUPlace,
    XPUPlace, CustomPlace, is_compiled_with_cuda, is_compiled_with_xpu,
    is_compiled_with_rocm, is_compiled_with_custom_device,
)

__all__ = ["get_device", "set_device", "device_count", "synchronize",
           "get_cudnn_version", "IPUPlace", "is_compiled_with_ipu",
           "is_compiled_with_cinn", "get_all_custom_device_type", "set_stream",
           "get_all_device_type", "get_available_device",
           "get_available_custom_device", "memory_allocated",
           "max_memory_allocated", "memory_reserved", "empty_cache", "Stream",
           "Event", "current_stream", "stream_guard", "force_cpu_backend"]


def force_cpu_backend(n_devices: int | None = None):
    """Pin jax to the host CPU backend, defending against the out-of-tree
    "axon" TPU-tunnel PJRT plugin whose factory can wedge `jax.backends()`
    even under JAX_PLATFORMS=cpu. Single source of truth for the workaround
    used by bench.py, __graft_entry__.py and tests/conftest.py.

    `n_devices` requests that many virtual CPU devices — only effective if
    jax has not initialized a backend yet (XLA_FLAGS is read at init)."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    try:
        import jax._src.xla_bridge as _xb
        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    return jax


def synchronize(device=None):
    """Block until all queued device work completes (XLA: fence via a tiny
    transfer, the analog of cudaDeviceSynchronize)."""
    (jax.device_put(0.0) + 0).block_until_ready()


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu", "tpu")]


def _mem_stats(device=None):
    d = jax.devices()[0] if device is None else device
    try:
        return d.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    return int(_mem_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    s = _mem_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    s = _mem_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def empty_cache():
    pass  # XLA owns the allocator; nothing to drop (parity no-op)


class Stream:
    """Parity object: XLA schedules its own streams; recorded for API compat
    (reference: paddle.device.Stream)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False):
        self.device = device

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


class cuda:
    """Namespace parity for paddle.device.cuda on TPU builds."""

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def device_count():
        return 0  # no CUDA in this build

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def get_device_name(device=None):
        """Reference device/cuda/__init__.py get_device_name; on a TPU
        build the accelerator is the TPU device."""
        import jax
        try:
            d = jax.devices()[0]
            return getattr(d, "device_kind", str(d))
        except Exception:
            return "cpu"

    @staticmethod
    def get_device_capability(device=None):
        """Reference get_device_capability returns (major, minor) compute
        capability; TPU/CPU have no CUDA CC — (0, 0) signals that like
        the reference does for unsupported devices."""
        return (0, 0)

    @staticmethod
    def get_device_properties(device=None):
        """Reference get_device_properties: a named struct with name,
        major, minor, total_memory (bytes)."""
        import collections
        import jax
        Props = collections.namedtuple(
            "_gpuDeviceProperties",
            ["name", "major", "minor", "total_memory", "multi_processor_count"])
        name = cuda.get_device_name(device)
        total = 0
        try:
            stats = jax.devices()[0].memory_stats() or {}
            total = int(stats.get("bytes_limit", 0))
        except Exception:
            pass
        return Props(name=name, major=0, minor=0, total_memory=total,
                     multi_processor_count=0)


def get_cudnn_version():
    """No cuDNN in a TPU build (reference returns None when absent)."""
    return None


class IPUPlace:
    """Name-compat placeholder (no IPU runtime in this build)."""

    def __repr__(self):
        return "Place(ipu)"


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    # XLA subsumes the CINN compiler in this build (SURVEY §7 mapping)
    return False


def get_all_custom_device_type():
    """Custom devices arrive as PJRT plugins; none registered by default."""
    return []


def set_stream(stream=None):
    """Streams are an XLA-runtime concern on TPU (no user-facing stream
    handles); accepted for script portability."""
    return stream


def backend_init_lock(timeout=None):
    """Shared flock serializing first TPU-backend init across processes
    (VERDICT r4 weak #3: the axon tunnel is single-client; two concurrent
    probes wedge each other). Returns the lock file handle (hold it for
    the process lifetime) or None when the lock file is unusable.

    bench.py, the bench watcher, and the kernel-proof harness all route
    through this; library users get it automatically by opting into TPU
    (the non-TPU default is the CPU backend, no tunnel contact)."""
    import fcntl
    import os
    import time as _time
    cap = float(timeout if timeout is not None
                else os.environ.get("BENCH_LOCK_TIMEOUT", "2400"))
    try:
        f = open("/tmp/paddle_tpu_bench.lock", "w")
    except OSError:
        return None
    deadline = _time.time() + cap
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            if _time.time() >= deadline:
                return f
            _time.sleep(5)


__all__ += ["backend_init_lock"]
