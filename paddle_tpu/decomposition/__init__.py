"""`paddle.decomposition` (reference: python/paddle/decomposition/decomp.py —
rewrites composite ops in a PIR program into the primitive-op set so the
autodiff/compiler layers only see primitives).

TPU-native: tracing IS decomposition — every framework op lowers through
jax into a jaxpr whose equations are the primitive set (add/mul/dot_general/
reduce_*/...). `decompose` exposes that program; `primitives_of` lists the
primitive vocabulary a callable uses, which is what the reference's
white-list machinery reasons about."""

from __future__ import annotations

__all__ = ['decompose', 'primitives_of', 'has_composite']


def _pure_fn(func, stop_gradient=False):
    """Lift a Tensor->Tensor callable to arrays->arrays (shared with
    paddle_tpu.cost_model; stop_gradient=True runs the whole call under
    no_grad — analysis-only traces must not build vjps, which also matters
    because `func` may close over Parameters that require grad)."""
    import contextlib

    from ..core.tensor import Tensor

    def f(*arrs):
        if stop_gradient:
            from ..autograd.grad_mode import no_grad
            ctx = no_grad()
        else:
            ctx = contextlib.nullcontext()
        with ctx:
            out = func(*[Tensor(a, stop_gradient=stop_gradient)
                         for a in arrs])
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    return f


def decompose(func, *example_args):
    """Trace ``func`` at ``example_args`` and return the primitive program
    (a jaxpr — the TPU analog of the decomposed PIR program)."""
    import jax

    from ..core.tensor import Tensor

    arrs = [a._data if isinstance(a, Tensor) else a for a in example_args]
    return jax.make_jaxpr(_pure_fn(func))(*arrs)


def primitives_of(func, *example_args):
    """Sorted primitive names used by ``func`` (transitively through inner
    closed-call jaxprs)."""
    jaxpr = decompose(func, *example_args)

    names = set()

    def descend(v):
        # params hold jaxprs directly, as ClosedJaxpr, or in tuples/lists
        # (e.g. lax.cond's 'branches')
        if isinstance(v, (tuple, list)):
            for item in v:
                descend(item)
            return
        inner = getattr(v, 'jaxpr', None)
        if inner is not None:
            walk(inner)
        elif hasattr(v, 'eqns'):
            walk(v)

    def walk(jx):
        for eqn in jx.eqns:
            names.add(eqn.primitive.name)
            for v in eqn.params.values():
                descend(v)
    walk(jaxpr.jaxpr)
    return sorted(names)


def has_composite(func, *example_args):
    """True if the traced program still contains ops the reference would
    decompose (here: named custom-vjp/checkpoint wrappers that hide their
    body from the primitive listing)."""
    prims = set(primitives_of(func, *example_args))
    # 'remat2' is jax's current checkpoint primitive name ('remat' kept for
    # older traces)
    return bool(prims & {'custom_vjp_call', 'custom_jvp_call', 'remat',
                         'remat2'})
