"""`paddle.decomposition` (reference: python/paddle/decomposition/decomp.py —
rewrites composite ops in a PIR program into the primitive-op set so the
autodiff/compiler layers only see primitives).

TPU-native: tracing IS decomposition — every framework op lowers through
jax into a jaxpr whose equations are the primitive set (add/mul/dot_general/
reduce_*/...). `decompose` exposes that program; `primitives_of` lists the
primitive vocabulary a callable uses, which is what the reference's
white-list machinery reasons about."""

from __future__ import annotations

__all__ = ['decompose', 'decompose_fn', 'primitives_of', 'has_composite']

# call-like primitives whose bodies `decompose` inlines (the TPU analog of
# the reference rewriting composite PIR ops into primitive ops,
# python/paddle/decomposition/decomp.py decompose): jit/pjit sub-programs,
# checkpoint wrappers, and custom-autodiff wrappers all hide primitive
# equations behind one opaque equation
_CALL_PRIMS = {
    "jit", "pjit", "closed_call", "core_call", "xla_call",
    "remat", "remat2", "checkpoint",
    "custom_vjp_call", "custom_jvp_call",
    "custom_vjp_call_jaxpr", "custom_jvp_call_jaxpr",
}


def _pure_fn(func, stop_gradient=False):
    """Lift a Tensor->Tensor callable to arrays->arrays (shared with
    paddle_tpu.cost_model; stop_gradient=True runs the whole call under
    no_grad — analysis-only traces must not build vjps, which also matters
    because `func` may close over Parameters that require grad)."""
    import contextlib

    from ..core.tensor import Tensor

    def f(*arrs):
        if stop_gradient:
            from ..autograd.grad_mode import no_grad
            ctx = no_grad()
        else:
            ctx = contextlib.nullcontext()
        with ctx:
            out = func(*[Tensor(a, stop_gradient=stop_gradient)
                         for a in arrs])
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    return f


def _inner_closed(eqn):
    """The ClosedJaxpr a call-like equation hides (param layouts differ by
    primitive and jax version: 'jaxpr' for jit/remat, 'call_jaxpr' for
    custom_vjp_call, 'fun_jaxpr' historically)."""
    from jax.extend import core as jex_core

    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        v = eqn.params.get(key)
        if v is None:
            continue
        if isinstance(v, jex_core.ClosedJaxpr):
            return v
        if hasattr(v, "eqns"):  # open jaxpr (remat2): no captured consts
            return jex_core.ClosedJaxpr(v, [])
    return None


def _inline_eval(closed, *args):
    """Evaluate a ClosedJaxpr, recursively inlining call-like equations so
    a retrace sees ONLY leaf primitives (the decompose rewrite)."""
    from jax.extend import core as jex_core

    jaxpr, consts = closed.jaxpr, closed.consts
    env = {}

    def read(var):
        return var.val if isinstance(var, jex_core.Literal) else env[var]

    for v, c in zip(jaxpr.constvars, consts):
        env[v] = c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a
    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        inner = _inner_closed(eqn) \
            if eqn.primitive.name in _CALL_PRIMS else None
        if inner is not None:
            outs = _inline_eval(inner, *invals)
        else:
            outs = eqn.primitive.bind(*invals, **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
        for v, val in zip(eqn.outvars, outs):
            env[v] = val
    return [read(v) for v in jaxpr.outvars]


def decompose_fn(func, *example_args):
    """Rewrite ``func`` into an equivalent callable whose trace contains
    only leaf primitives — jit bodies, checkpoint wrappers, and
    custom-vjp/jvp wrappers are inlined (custom gradient rules are
    REPLACED by primitive autodiff, exactly the reference's composite->
    primitive contract for prim-based higher-order autodiff). Returns
    (fn, arrays) ready for jax tracing/transforms."""
    import jax

    from ..core.tensor import Tensor

    arrs = [a._data if isinstance(a, Tensor) else a for a in example_args]
    raw = jax.make_jaxpr(_pure_fn(func))(*arrs)

    def inlined(*xs):
        out = _inline_eval(raw, *xs)
        return out[0] if len(out) == 1 else tuple(out)

    return inlined, arrs


def decompose(func, *example_args, whitelist=None):
    """Trace ``func`` at ``example_args`` and return the PRIMITIVE program:
    a jaxpr in which every call-like composite (jit/pjit, checkpoint,
    custom-vjp/jvp) has been inlined (reference decomp.py `decompose`
    rewriting a PIR program to the primitive set).

    `whitelist`: optional iterable of allowed primitive names — the
    reference's white-list contract. Any equation outside it raises
    ValueError naming the offenders."""
    import jax

    inlined, arrs = decompose_fn(func, *example_args)
    out = jax.make_jaxpr(inlined)(*arrs)
    if whitelist is not None:
        # transitive: control-flow primitives (cond/scan/while) legally
        # keep sub-jaxprs — their bodies are checked too. No exemption for
        # call prims: a successfully inlined program has none left, and a
        # wrapper _inner_closed failed to recognize must be flagged, not
        # silently passed
        used = _collect_primitive_names(out.jaxpr)
        bad = sorted(used - set(whitelist))
        if bad:
            raise ValueError(
                f"decompose: primitives outside the whitelist: {bad}")
    return out


def _collect_primitive_names(jx):
    """Primitive names of a (open) jaxpr, transitively through params
    holding jaxprs directly, as ClosedJaxpr, or in tuples/lists (e.g.
    lax.cond's 'branches')."""
    names = set()

    def descend(v):
        if isinstance(v, (tuple, list)):
            for item in v:
                descend(item)
            return
        inner = getattr(v, 'jaxpr', None)
        if inner is not None:
            walk(inner)
        elif hasattr(v, 'eqns'):
            walk(v)

    def walk(j):
        for eqn in j.eqns:
            names.add(eqn.primitive.name)
            for v in eqn.params.values():
                descend(v)
    walk(jx)
    return names


def primitives_of(func, *example_args):
    """Sorted primitive names used by ``func`` (transitively through inner
    closed-call jaxprs). Walks the RAW trace — call-like wrappers appear
    by name (so has_composite can detect them), their bodies too."""
    import jax

    from ..core.tensor import Tensor
    arrs = [a._data if isinstance(a, Tensor) else a for a in example_args]
    jaxpr = jax.make_jaxpr(_pure_fn(func))(*arrs)
    return sorted(_collect_primitive_names(jaxpr.jaxpr))


def has_composite(func, *example_args):
    """True if the traced program still contains ops the reference would
    decompose (here: named custom-vjp/checkpoint wrappers that hide their
    body from the primitive listing)."""
    prims = set(primitives_of(func, *example_args))
    # 'remat2' is jax's current checkpoint primitive name ('remat' kept for
    # older traces)
    return bool(prims & {'custom_vjp_call', 'custom_jvp_call', 'remat',
                         'remat2'})
