"""Machine topology description for the parallelism planner.

A :class:`Topology` describes the ICI/DCN hierarchy the plan must respect:
how many chips, how many chips share one ICI domain (a *slice*), and the
bandwidth/latency of each tier. The planner uses it two ways:

* **placement** — mesh axes are laid out major-to-minor in the fixed order
  ``[dp, pp, sharding, sep, mp]`` (:mod:`paddle_tpu.distributed.topology`
  orders the jax mesh the same way), so an axis's communication groups
  span a contiguous device range whose extent is ``degree * stride``
  (stride = product of the dims minor to it). :meth:`Topology.axis_link`
  resolves whether that range stays inside one slice (ICI) or crosses
  slices (DCN) — dp, the outermost axis, is the one allowed to be slow;
* **pricing** — the resolved :class:`~paddle_tpu.cost_model.LinkSpec`
  feeds the alpha-beta collective formulas in
  :mod:`paddle_tpu.cost_model.collective`.

Spec strings (CLI ``--topology``, :meth:`Topology.from_spec`):

* ``"v5e:16x2"`` — 2 DCN-connected slices of 16 v5e chips (32 total);
* ``"v4:8"`` — one 8-chip v4 slice (no DCN);
* ``"cpu:8"`` — the virtual 8-device CPU test mesh;
* ``"chips=32,slice=16,ici_gbps=186,dcn_gbps=25,hbm_gb=16,
  peak_tflops=197"`` — fully custom key=value form.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..cost_model.collective import CHIP_PRESETS, LinkSpec, chip_preset

__all__ = ["Topology", "MESH_AXES"]

#: fixed major-to-minor mesh axis order (mirrors fleet.init's default
#: hybrid order; mp innermost so tensor-parallel traffic rides neighbors)
MESH_AXES = ("dp", "pp", "sharding", "sep", "mp")


@dataclass
class Topology:
    chips: int
    slice_chips: int                  # chips per ICI domain
    ici: LinkSpec = field(default_factory=lambda: CHIP_PRESETS["cpu"]["ici"])
    dcn: LinkSpec = field(default_factory=lambda: CHIP_PRESETS["cpu"]["dcn"])
    hbm_bytes: int = 4 << 30          # per-chip HBM budget
    peak_flops: float = 5e10          # per-chip dense peak
    name: str = "custom"

    def __post_init__(self):
        if self.chips < 1:
            raise ValueError(f"chips must be >= 1, got {self.chips}")
        if self.slice_chips < 1 or self.chips % self.slice_chips:
            raise ValueError(
                f"slice_chips ({self.slice_chips}) must divide chips "
                f"({self.chips})")

    @property
    def n_slices(self) -> int:
        return self.chips // self.slice_chips

    # -- construction -------------------------------------------------------
    @classmethod
    def from_spec(cls, spec: str, chips: int | None = None) -> "Topology":
        """Parse a topology spec string (module docstring grammar).

        ``chips`` overrides/supplies the total count for preset forms
        like ``"v5e"`` with no explicit shape.
        """
        spec = (spec or "cpu").strip()
        if "=" in spec:
            kv = {}
            for part in spec.split(","):
                k, _, v = part.partition("=")
                kv[k.strip()] = v.strip()
            n = int(kv.get("chips", chips or 1))
            if chips is not None and int(chips) != n:
                raise ValueError(
                    f"--chips {chips} contradicts topology {spec!r} "
                    f"({n} chips)")
            return cls(
                chips=n,
                slice_chips=int(kv.get("slice", n)),
                ici=LinkSpec(float(kv.get("ici_gbps", 10.0)),
                             float(kv.get("ici_us", 1.0))),
                dcn=LinkSpec(float(kv.get("dcn_gbps", 1.0)),
                             float(kv.get("dcn_us", 50.0))),
                hbm_bytes=int(float(kv.get("hbm_gb", 4.0)) * (1 << 30)),
                peak_flops=float(kv.get("peak_tflops", 0.05)) * 1e12,
                name="custom")
        preset_name, _, shape = spec.partition(":")
        preset = chip_preset(preset_name)
        if shape:
            if "x" in shape:
                per_slice, n_slices = (int(p) for p in shape.split("x"))
            else:
                per_slice, n_slices = int(shape), 1
            total = per_slice * n_slices
        else:
            total = int(chips or 1)
            per_slice = total
        if chips is not None and int(chips) != total:
            raise ValueError(
                f"--chips {chips} contradicts topology {spec!r} "
                f"({total} chips)")
        return cls(chips=total, slice_chips=per_slice,
                   ici=preset["ici"], dcn=preset["dcn"],
                   hbm_bytes=int(preset["hbm_gb"] * (1 << 30)),
                   peak_flops=preset["peak_flops"], name=preset_name)

    # -- placement ----------------------------------------------------------
    def axis_stride(self, axis: str, dims: dict) -> int:
        """Device-index stride between neighbors along ``axis`` for a mesh
        with degrees ``dims`` laid out in MESH_AXES order."""
        stride = 1
        for a in reversed(MESH_AXES):
            if a == axis:
                return stride
            stride *= int(dims.get(a, 1))
        raise ValueError(f"unknown mesh axis {axis!r}")

    def axis_on_ici(self, axis: str, dims: dict) -> bool:
        """True when every communication group along ``axis`` fits inside
        one ICI slice: the group's contiguous device extent
        (``degree * stride``) divides the slice size, so no member pair
        straddles a slice boundary."""
        degree = int(dims.get(axis, 1))
        if degree <= 1:
            return True
        extent = degree * self.axis_stride(axis, dims)
        return extent <= self.slice_chips and \
            self.slice_chips % extent == 0

    def axis_link(self, axis: str, dims: dict) -> LinkSpec:
        return self.ici if self.axis_on_ici(axis, dims) else self.dcn

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {"name": self.name, "chips": self.chips,
                "slice_chips": self.slice_chips,
                "ici": self.ici.to_dict(), "dcn": self.dcn.to_dict(),
                "hbm_bytes": int(self.hbm_bytes),
                "peak_flops": float(self.peak_flops)}

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        return cls(chips=int(d["chips"]),
                   slice_chips=int(d["slice_chips"]),
                   ici=LinkSpec(**d["ici"]), dcn=LinkSpec(**d["dcn"]),
                   hbm_bytes=int(d["hbm_bytes"]),
                   peak_flops=float(d["peak_flops"]),
                   name=d.get("name", "custom"))
