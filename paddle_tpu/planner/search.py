"""Cost-modeled plan search: enumerate → prune → memory-fit → score.

The pipeline reuses every search primitive the repo already has, in the
order the ISSUE names them:

1. **enumerate** — :func:`paddle_tpu.auto_tuner.default_candidates` over
   every ``(dp, pp, sharding, sep, mp, micro_batch)`` factorization of
   the chip count;
2. **prune** — :func:`paddle_tpu.auto_tuner.prune_by_divisibility` with
   the model's head/kv-head/layer/vocab/seq divisibility constraints;
3. **placement filter** — mp and sep must ride ICI
   (:meth:`Topology.axis_on_ici`); dp is the axis allowed to cross DCN,
   and is priced with the DCN link when it does;
4. **memory-fit filter** — the analyzer's static peak-HBM
   (``ModelDesc.act_peak_bytes_per_sample`` from the liveness pass)
   scaled per candidate must fit the per-chip budget, trying the
   recompute policy before rejecting — infeasible candidates are
   REJECTED BEFORE SCORING;
5. **score** — alpha-beta collective costs
   (:mod:`paddle_tpu.cost_model.collective`) over the per-axis implied
   collectives + roofline compute time + pipeline bubble.

Every stage increments ``paddle_tpu_planner_candidates_total{stage=}``;
the whole search records ``paddle_tpu_planner_search_seconds``. Memory
and time formulas are documented in docs/parallelism_planner.md and
unit-tested against hand-computed values in tests/test_planner.py.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..auto_tuner import Candidate, default_candidates, \
    prune_by_divisibility
from ..cost_model.collective import (all_gather_s, all_reduce_s,
                                     all_to_all_s, p2p_s, reduce_scatter_s)
from .describe import ModelDesc
from .plan import Plan, build_specs
from .topology import MESH_AXES, Topology

__all__ = ["plan_search", "ScoredCandidate", "PlannerResult",
           "predict_memory", "predict_step_time"]

#: fraction of per-chip HBM a plan may claim (allocator + runtime slack)
HBM_UTIL = 0.92
#: achievable fraction of dense peak FLOPs (MFU target the roofline uses)
MFU_TARGET = 0.5
#: optimizer state elements per parameter (Adam: two f32 moments)
OPT_SLOTS = 2


def _dims_of(cand: Candidate) -> dict:
    return {"dp": cand.dp, "pp": cand.pp, "sharding": cand.sharding,
            "sep": cand.sep, "mp": cand.mp}


def predict_memory(desc: ModelDesc, cand: Candidate, topo: Topology,
                   global_batch: int, recompute: bool) -> dict:
    """Per-chip HBM claim of a candidate (bytes, documented upper bound).

    * params: ``param_bytes / (mp * pp)``, ZeRO-3 over sharding;
    * grads: one f32 copy, ZeRO >= 2 shards it over sharding;
    * optimizer: OPT_SLOTS f32 moments, ZeRO >= 1 shards them;
    * activations: the liveness pass's per-sample intermediate peak
      scaled to the micro-batch, divided over sep (sequence) and pp
      (layers per stage), times the 1F1B in-flight stash factor
      ``min(pp, micro_batches)``; with recompute only the per-layer
      boundary tensors are stashed plus one layer's working set.
    """
    mp, pp, sh = cand.mp, cand.pp, cand.sharding
    m = cand.micro_batch
    mbs = max(global_batch // (cand.dp * sh * m), 1)

    params = desc.param_bytes / (mp * pp * sh)
    grads = desc.param_count * 4 / (mp * pp * sh)
    opt = OPT_SLOTS * desc.param_count * 4 / (mp * pp * sh)

    act_mb = desc.act_peak_bytes_per_sample * mbs / (cand.sep * pp)
    inflight = min(pp, m) if pp > 1 else 1
    if recompute:
        # stash = residual-stream boundary per layer per in-flight mb
        boundary = (desc.num_layers / pp) * mbs * desc.seq_len * \
            desc.hidden_size * desc.dtype_bytes / cand.sep
        one_layer = act_mb / max(desc.num_layers / pp, 1)
        act = boundary * inflight + one_layer
    else:
        act = act_mb * inflight

    total = params + grads + opt + act
    return {"params_bytes": int(params), "grads_bytes": int(grads),
            "opt_bytes": int(opt), "act_bytes": int(act),
            "total_bytes": int(total), "micro_batch_size": mbs,
            "budget_bytes": int(topo.hbm_bytes * HBM_UTIL),
            "fits": total <= topo.hbm_bytes * HBM_UTIL}


def predict_step_time(desc: ModelDesc, cand: Candidate, topo: Topology,
                      global_batch: int, recompute: bool) -> dict:
    """Analytic step time: roofline compute × pipeline bubble + the
    alpha-beta cost of every implied collective, priced on the link each
    axis actually rides (ICI vs DCN). No comm/compute overlap is assumed
    — the result is an ordering bound, not a simulation."""
    dims = _dims_of(cand)
    mp, pp, sh, sep, dp = cand.mp, cand.pp, cand.sharding, cand.sep, cand.dp
    m = cand.micro_batch
    mbs = max(global_batch // (dp * sh * m), 1)

    # compute: fwd + 2x bwd (+1 fwd when recomputing), split over the mesh
    passes = 4.0 if recompute else 3.0
    flops_per_chip = passes * desc.flops_fwd_per_sample * global_batch \
        / cand.world
    compute_s = flops_per_chip / (topo.peak_flops * MFU_TARGET)
    bubble_factor = (m + pp - 1) / m
    bubble_s = compute_s * (bubble_factor - 1.0)

    layers_per_stage = max(desc.num_layers // pp, 1)
    act_mb = mbs * desc.seq_len * desc.hidden_size * desc.dtype_bytes / sep
    comm = []

    def add(op, axis, count, nbytes, seconds):
        if count and seconds > 0:
            comm.append({"op": op, "axis": axis, "count": int(count),
                         "bytes": int(nbytes),
                         "seconds": float(seconds * count)})

    # mp: Megatron f/g pairs — 2 activation all-reduces per layer per
    # direction (attention out-proj + MLP down-proj), fwd + bwd
    if mp > 1:
        link = topo.axis_link("mp", dims)
        count = 4 * layers_per_stage * m
        add("all-reduce", "mp", count, act_mb,
            all_reduce_s(act_mb, mp, link))
    # sep (Ulysses): seq<->heads all-to-alls around each attention,
    # 2 fwd + 2 bwd per layer
    if sep > 1:
        link = topo.axis_link("sep", dims)
        count = 4 * layers_per_stage * m
        add("all-to-all", "sep", count, act_mb,
            all_to_all_s(act_mb, sep, link))
    # pp: boundary activation p2p, fwd + bwd, per micro-batch
    if pp > 1:
        link = topo.axis_link("pp", dims)
        count = 2 * m
        add("p2p", "pp", count, act_mb, p2p_s(act_mb, link))
    # dp: gradient all-reduce once per step (bucketed); under ZeRO each
    # chip only reduces its 1/sh grad shard over dp
    grad_bytes = desc.param_count * 4 / (mp * pp)
    if dp > 1:
        link = topo.axis_link("dp", dims)
        add("all-reduce", "dp", 1, grad_bytes / sh,
            all_reduce_s(grad_bytes / sh, dp, link))
    # sharding (ZeRO-3): reduce-scatter grads + re-gather params for the
    # next step's fwd and bwd
    if sh > 1:
        link = topo.axis_link("sharding", dims)
        add("reduce-scatter", "sharding", 1, grad_bytes,
            reduce_scatter_s(grad_bytes, sh, link))
        add("all-gather", "sharding", 2, desc.param_bytes / (mp * pp),
            all_gather_s(desc.param_bytes / (mp * pp), sh, link))

    comm_s = sum(c["seconds"] for c in comm)
    total = compute_s + bubble_s + comm_s
    return {"compute_s": float(compute_s), "bubble_s": float(bubble_s),
            "comm_s": float(comm_s), "step_time_s": float(total),
            "bubble_fraction": float((pp - 1) / (m + pp - 1)) if pp > 1
            else 0.0,
            "tokens_per_s": float(global_batch * desc.seq_len
                                  / max(total, 1e-12)),
            "comm": comm}


@dataclass
class ScoredCandidate:
    candidate: Candidate
    feasible: bool = True
    reject_reason: str = ""
    recompute: bool = False
    score: float = float("inf")      # predicted step seconds
    predicted: dict = field(default_factory=dict)
    memory: dict = field(default_factory=dict)

    def mesh_dict(self) -> dict:
        return _dims_of(self.candidate)

    def key(self) -> tuple:
        c = self.candidate
        return (c.dp, c.pp, c.sharding, c.sep, c.mp, c.micro_batch)

    def to_dict(self) -> dict:
        return {"mesh": self.mesh_dict(),
                "micro_batches": self.candidate.micro_batch,
                "feasible": self.feasible,
                "reject_reason": self.reject_reason,
                "recompute": self.recompute,
                "score_s": None if self.score == float("inf")
                else float(self.score),
                "predicted": self.predicted, "memory": self.memory}


@dataclass
class PlannerResult:
    plans: list = field(default_factory=list)       # top-k Plan, ranked
    scored: list = field(default_factory=list)      # every ScoredCandidate
    n_enumerated: int = 0
    n_pruned: int = 0
    n_placement_rejected: int = 0
    n_memory_rejected: int = 0
    n_scored: int = 0
    search_seconds: float = 0.0

    @property
    def best(self):
        return self.plans[0] if self.plans else None

    def ranking(self) -> list:
        """Feasible candidates, best first."""
        return sorted((s for s in self.scored if s.feasible),
                      key=lambda s: s.score)

    def rank_of(self, mesh: dict, micro_batches: int | None = None):
        """0-based rank of a (hand-tuned) config in the planner's
        ordering, or None when it was pruned/rejected. ``mesh`` uses the
        axis-name keys; omitted axes default to 1; omitted
        ``micro_batches`` matches that mesh's best micro-batch count."""
        want = tuple(int(mesh.get(a, 1)) for a in MESH_AXES)
        for i, s in enumerate(self.ranking()):
            got = tuple(int(s.mesh_dict()[a]) for a in MESH_AXES)
            if got == want and (micro_batches is None or
                                s.candidate.micro_batch == micro_batches):
                return i
        return None

    def to_dict(self, top_scored: int = 10) -> dict:
        return {
            "plans": [p.to_dict() for p in self.plans],
            "ranking": [s.to_dict() for s in self.ranking()[:top_scored]],
            "rejected": [s.to_dict() for s in self.scored
                         if not s.feasible][:top_scored],
            "n_enumerated": self.n_enumerated,
            "n_pruned": self.n_pruned,
            "n_placement_rejected": self.n_placement_rejected,
            "n_memory_rejected": self.n_memory_rejected,
            "n_scored": self.n_scored,
            "search_seconds": round(self.search_seconds, 4),
        }


def _metrics():
    from ..observability import metrics as m
    return m


def plan_search(model=None, topology="cpu:8", global_batch=32,
                seq_len=None, micro_batches=(1, 2, 4, 8), top=3,
                desc: ModelDesc | None = None, max_sep: int | None = None,
                hbm_budget_bytes: int | None = None) -> PlannerResult:
    """Search the 5-D mesh space for ``model`` on ``topology``.

    ``model`` is an ``nn.Layer`` (GPT/Llama style config) — or pass a
    prebuilt ``desc``. ``topology`` is a spec string or
    :class:`Topology`. Returns a :class:`PlannerResult` whose ``plans``
    are the top-k feasible candidates as full :class:`Plan` objects.
    """
    t0 = time.perf_counter()
    topo = topology if isinstance(topology, Topology) \
        else Topology.from_spec(topology)
    if hbm_budget_bytes is not None:
        # explicit budget override (tests pin tiny budgets to prove the
        # memory filter fires)
        topo = Topology(chips=topo.chips, slice_chips=topo.slice_chips,
                        ici=topo.ici, dcn=topo.dcn,
                        hbm_bytes=int(hbm_budget_bytes),
                        peak_flops=topo.peak_flops, name=topo.name)
    if desc is None:
        if model is None:
            raise ValueError("pass a model or a prebuilt ModelDesc")
        if seq_len is None:
            raise ValueError("seq_len is required when tracing a model")
        desc = ModelDesc.from_model(model, seq_len)
    seq_len = desc.seq_len

    m = _metrics()
    cand_counter = m.counter("paddle_tpu_planner_candidates_total",
                             "planner candidates by pipeline stage")
    chips = topo.chips
    cands = default_candidates(
        chips, max_mp=chips, max_pp=min(chips, desc.num_layers),
        micro_batches=tuple(micro_batches),
        max_sep=chips if max_sep is None else max_sep)
    result = PlannerResult(n_enumerated=len(cands))
    cand_counter.inc(len(cands), stage="enumerated")

    kept = prune_by_divisibility(
        cands, num_layers=desc.num_layers, num_heads=desc.num_heads,
        global_batch=global_batch, num_kv_heads=desc.num_kv_heads,
        vocab_size=desc.vocab_size, seq_len=seq_len)
    result.n_pruned = len(cands) - len(kept)
    cand_counter.inc(result.n_pruned, stage="pruned")

    for cand in kept:
        dims = _dims_of(cand)
        sc = ScoredCandidate(candidate=cand)
        # placement: fast axes must stay on ICI (DCN-awareness)
        slow = [a for a in ("mp", "sep") if not topo.axis_on_ici(a, dims)]
        if slow:
            sc.feasible = False
            sc.reject_reason = f"{'/'.join(slow)} crosses DCN"
            result.n_placement_rejected += 1
            cand_counter.inc(stage="placement_rejected")
            result.scored.append(sc)
            continue
        # memory-fit BEFORE scoring, recompute only if needed
        mem = predict_memory(desc, cand, topo, global_batch,
                             recompute=False)
        if not mem["fits"]:
            mem_rc = predict_memory(desc, cand, topo, global_batch,
                                    recompute=True)
            if mem_rc["fits"]:
                sc.recompute, mem = True, mem_rc
            else:
                sc.feasible = False
                sc.reject_reason = (
                    f"does not fit HBM: {mem_rc['total_bytes']} > "
                    f"{mem_rc['budget_bytes']} even with recompute")
                sc.memory = mem_rc
                result.n_memory_rejected += 1
                cand_counter.inc(stage="memory_rejected")
                result.scored.append(sc)
                continue
        sc.memory = mem
        sc.predicted = predict_step_time(desc, cand, topo, global_batch,
                                         recompute=sc.recompute)
        sc.score = sc.predicted["step_time_s"]
        result.n_scored += 1
        cand_counter.inc(stage="scored")
        result.scored.append(sc)

    for sc in result.ranking()[:max(top, 1)]:
        result.plans.append(_as_plan(sc, desc, topo, global_batch))

    result.search_seconds = time.perf_counter() - t0
    m.histogram("paddle_tpu_planner_search_seconds",
                "wall seconds per plan_search call").observe(
        result.search_seconds)
    if result.plans:
        m.gauge("paddle_tpu_planner_chosen_score_s",
                "predicted step seconds of the chosen plan").set(
            result.plans[0].predicted["step_time_s"])
    return result


def _as_plan(sc: ScoredCandidate, desc: ModelDesc, topo: Topology,
             global_batch: int) -> Plan:
    cand = sc.candidate
    pp = cand.pp
    per = desc.num_layers // pp
    stages = [per] * pp
    for i in range(desc.num_layers - per * pp):
        stages[i] += 1
    predicted = dict(sc.predicted)
    predicted["per_chip_hbm_bytes"] = sc.memory["total_bytes"]
    predicted["memory"] = sc.memory
    return Plan(
        mesh=_dims_of(cand),
        specs=build_specs(cand.mp),
        schedule={"micro_batches": cand.micro_batch,
                  "schedule_mode": "1F1B" if pp > 1 else "none",
                  "stages": stages},
        recompute={"enable": bool(sc.recompute),
                   "policy": "full" if sc.recompute else "none"},
        global_batch=int(global_batch), seq_len=int(desc.seq_len),
        model=desc.to_dict(), topology=topo.to_dict(),
        predicted=predicted)
