"""CLI: ``python -m paddle_tpu.planner --model gpt-tiny --topology cpu:8``.

Plans a registered model config on a described topology and prints the
ranked candidates (text table or JSON). ``--validate`` proves the chosen
plan's collective counts against compiled HLO on the local mesh (needs
the plan's world <= local device count); ``--measured`` re-ranks the
top-k by real timed trials on the local mesh.
"""

from __future__ import annotations

import argparse
import json
import sys

MODELS = ("gpt-tiny", "llama-tiny", "bench-gpt")
#: per-model default (global_batch, seq_len) for CPU-mesh planning
_DEFAULTS = {"gpt-tiny": (32, 32), "llama-tiny": (32, 32),
             "bench-gpt": (32, 256)}


def build_model(name: str):
    import paddle_tpu as paddle
    paddle.seed(0)
    if name == "gpt-tiny":
        from paddle_tpu.models import gpt2_tiny
        return gpt2_tiny()
    if name == "llama-tiny":
        from paddle_tpu.models import Llama, LlamaConfig
        return Llama(LlamaConfig(
            vocab_size=256, max_position_embeddings=64, hidden_size=64,
            num_layers=2, num_heads=4, num_kv_heads=2,
            intermediate_size=128))
    if name == "bench-gpt":
        from paddle_tpu.models import GPT, GPTConfig
        return GPT(GPTConfig(vocab_size=1024, max_position_embeddings=256,
                             hidden_size=256, num_layers=4, num_heads=8))
    raise SystemExit(f"unknown --model {name!r} (have {', '.join(MODELS)})")


def _measured_build(model_name: str, plan_obj):
    """(step, args) for one measured trial: fresh model, plan applied."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.planner import apply_plan

    if plan_obj.degree("pp") > 1:
        raise RuntimeError("measured trials for pp > 1 need a pipeline "
                           "model; skipped")
    model = build_model(model_name)
    # the WRAPPED model: its forward shards positional inputs over
    # dp/sharding/sep, so the timed program is the plan's program (a bare
    # model would run replicated inputs and emit no dp collectives)
    wrapped = apply_plan(model, plan_obj)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    rng = np.random.default_rng(0)
    vocab = plan_obj.model.get("vocab_size", 256)
    b = max(plan_obj.micro_batch_size(), 1)
    s = plan_obj.seq_len
    x = paddle.to_tensor(rng.integers(0, vocab, (b, s)).astype("int32"))
    y = paddle.to_tensor(rng.integers(0, vocab, (b, s)).astype("int32"))

    @paddle.jit.to_static
    def step(x, y):
        _, loss = wrapped(x, y)  # positional: labels get sharded too
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    return step, (x, y)


def _text_report(result, args, validation) -> str:
    lines = [f"planner: {args.model} on {args.topology} "
             f"(global_batch={args.global_batch}, seq={args.seq})",
             f"  enumerated {result.n_enumerated}, pruned "
             f"{result.n_pruned}, placement-rejected "
             f"{result.n_placement_rejected}, memory-rejected "
             f"{result.n_memory_rejected}, scored {result.n_scored} in "
             f"{result.search_seconds * 1e3:.1f} ms", ""]
    hdr = (f"  {'rank':<5}{'mesh':<38}{'pred ms':>9}{'tok/s':>12}"
           f"{'HBM MiB':>9}")
    lines.append(hdr)
    for i, sc in enumerate(result.ranking()[:args.top]):
        p = sc.predicted
        lines.append(
            f"  {i:<5}{sc.candidate!r:<38}"
            f"{p['step_time_s'] * 1e3:>9.2f}"
            f"{p['tokens_per_s']:>12.0f}"
            f"{sc.memory['total_bytes'] / (1 << 20):>9.1f}"
            + ("  +recompute" if sc.recompute else ""))
    best = result.best
    if best is not None:
        lines += ["", f"  chosen: {best.summary()}  "
                      f"fingerprint={best.fingerprint()}"]
        for c in best.predicted.get("comm", []):
            lines.append(
                f"    {c['op']}@{c['axis']}: {c['count']}x "
                f"{c['bytes'] / (1 << 20):.2f} MiB -> "
                f"{c['seconds'] * 1e3:.3f} ms")
        if "measured_step_s" in best.predicted:
            lines.append(
                f"  measured: {best.predicted['measured_step_s'] * 1e3:.2f}"
                f" ms/step vs predicted "
                f"{best.predicted['step_time_s'] * 1e3:.2f} ms")
    if validation is not None:
        lines.append(f"  validation: "
                     f"{'OK' if validation.ok else 'MISMATCH'}")
        for c in validation.failures():
            lines.append(f"    FAIL {c}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.planner",
        description="plan 5-D parallelism for a model on a topology")
    ap.add_argument("--model", default="gpt-tiny",
                    help=f"one of {', '.join(MODELS)}")
    ap.add_argument("--chips", type=int, default=None,
                    help="total chip count (when --topology has no shape)")
    ap.add_argument("--topology", default="cpu:8",
                    help="e.g. v5e:16x2, v4:8, cpu:8, or key=value form")
    ap.add_argument("--global-batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--top", type=int, default=5)
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--validate", action="store_true",
                    help="prove the chosen plan's collective counts "
                         "against compiled HLO on the local mesh")
    ap.add_argument("--measured", action="store_true",
                    help="re-rank the top plans by real timed trials")
    ap.add_argument("--out", default=None,
                    help="also write the chosen plan's JSON here")
    args = ap.parse_args(argv)

    from paddle_tpu.planner import (Topology, plan_search, refine_plans,
                                    validate_plan)

    gb_default, seq_default = _DEFAULTS.get(args.model, (32, 32))
    args.global_batch = args.global_batch or gb_default
    args.seq = args.seq or seq_default

    topo = Topology.from_spec(args.topology, chips=args.chips)
    model = build_model(args.model)
    result = plan_search(model, topology=topo,
                         global_batch=args.global_batch,
                         seq_len=args.seq, top=args.top)
    if not result.plans:
        print("planner: NO feasible plan", file=sys.stderr)
        for sc in result.scored[:10]:
            print(f"  {sc.candidate!r}: {sc.reject_reason}",
                  file=sys.stderr)
        return 1

    if args.measured:
        refine_plans(result,
                     lambda p: _measured_build(args.model, p),
                     mode="measured", top=args.top)

    validation = None
    if args.validate:
        validation = validate_plan(result.best)

    if args.out:
        with open(args.out, "w") as f:
            f.write(result.best.to_json())
    if args.format == "json":
        payload = result.to_dict(top_scored=args.top)
        if validation is not None:
            payload["validation"] = validation.to_dict()
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(_text_report(result, args, validation))
    return 0 if validation is None or validation.ok else 1


if __name__ == "__main__":
    sys.exit(main())
