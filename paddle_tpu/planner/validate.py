"""Plan validation: every emitted plan is PROVED, not trusted.

Two checks, in the PR 6 proof style (compiled HLO is the ground truth):

* **collective-count proof** — for each parallel axis the plan uses, a
  minimal probe program exercising that axis's implied collective is
  compiled ON THE TEST MESH (the plan's mesh shape over the local
  devices) and the collectives in the HLO text are counted per
  (op-class, axis-group). The observed count must EQUAL the predicted
  count, and the instances' ``replica_groups`` must be exactly the
  axis's communication groups (:class:`CommunicateTopology` semantics:
  groups vary one axis, fix the others). Op classes absorb backend
  lowering freedom the same way PR 6's proofs do — XLA:CPU lowers
  reduce-scatter as all-reduce(+slice) and may lower all-to-all as
  all-gather(+slice); either is still exactly ONE reshard collective.

* **memory-fit proof** — the plan's predicted per-chip HBM claim must
  fit the topology's budget (the search already filtered on this; the
  validator re-asserts it so a hand-edited/deserialized plan cannot
  smuggle an OOM config past the gate).

Probes (each compiled with ``jax.jit`` + ``NamedSharding`` avals, no
device execution):

=========  =====================================================  ========
axis       probe program                                          predicts
=========  =====================================================  ========
mp         Megatron pair: x @ W_col -> constraint -> @ W_row      1 all-reduce
dp         grad of sum((x_dp @ W)^2) wrt replicated W             1 all-reduce
sharding   forward gather of a dim-0-sharded param (ZeRO-3)       1 all-gather
sharding   grad wrt a dim-0-sharded param, batch sharded          1 grad-reduce
sep        reshard [b,s,h,d] seq-shard -> head-shard (Ulysses)    1 reshard
pp         shard_map ppermute ring over the pp axis               1 permute
=========  =====================================================  ========
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

from .plan import Plan
from .topology import MESH_AXES

__all__ = ["validate_plan", "ValidationReport", "count_hlo_collectives",
           "axis_groups"]

#: op equivalence classes: predicted op -> the HLO op names that satisfy it
OP_CLASSES = {
    "all-reduce": ("all-reduce",),
    "all-gather": ("all-gather",),
    # XLA:CPU lowers reduce-scatter as all-reduce + slice
    "grad-reduce": ("reduce-scatter", "all-reduce"),
    # some lowerings use all-gather (+ local slice) for a reshard
    "reshard": ("all-to-all", "all-gather"),
    "permute": ("collective-permute",),
}

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "all-to-all",
                   "reduce-scatter", "collective-permute")
# opcode occurrences only: `all-reduce(`, not the instruction NAME
# (`%all-reduce.1 = ...`, excluded by the lookbehind) and not metadata
# op_names (underscored). Async pairs count once: -start is the
# instance, -done the completion marker. Tuple-typed instructions print
# `/*index=N*/` comments inside the result type, so the opcode cannot be
# anchored on the `=` sign.
_DEF_RE = re.compile(
    r"(?<!%)\b(" + "|".join(_COLLECTIVE_OPS) + r")(-start|-done)?\(")
_GROUPS_ATTR_RE = re.compile(
    r"(replica_groups|source_target_pairs)=(\{\{[^}]*(?:\},\{[^}]*)*\}\}"
    r"|\{[0-9, ]*\}|\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")


def axis_groups(dims: dict, axis: str) -> frozenset:
    """Communication groups along ``axis`` for a mesh with ``dims`` laid
    out in MESH_AXES order, as a frozenset of device-id tuples — the
    same groups ``CommunicateTopology.get_comm_list`` derives."""
    shape = tuple(int(dims.get(a, 1)) for a in MESH_AXES)
    grid = np.arange(int(np.prod(shape))).reshape(shape)
    ax = MESH_AXES.index(axis)
    moved = np.moveaxis(grid, ax, -1).reshape(-1, shape[ax])
    return frozenset(tuple(int(r) for r in row) for row in moved)


def _parse_groups(attr: str):
    """``replica_groups`` / ``source_target_pairs`` text -> frozenset of
    tuples. Handles the explicit ``{{0,1},{2,3}}`` form and the iota form
    ``[G,S]<=[A,B]T(perm)``."""
    attr = attr.strip()
    if attr.startswith("{"):
        rows = re.findall(r"\{([0-9,\s]+)\}", attr)
        if not rows and attr != "{}":
            inner = attr.strip("{}").strip()
            rows = [inner] if inner else []
        return frozenset(
            tuple(int(x) for x in row.replace(" ", "").split(",") if x)
            for row in rows)
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", attr)
    if not m:
        return frozenset()
    dst = [int(x) for x in m.group(1).split(",")]
    src = [int(x) for x in m.group(2).split(",")]
    arr = np.arange(int(np.prod(src))).reshape(src)
    if m.group(3):
        arr = arr.transpose([int(x) for x in m.group(3).split(",")])
    arr = arr.reshape(dst)
    return frozenset(tuple(int(x) for x in row) for row in arr)


def count_hlo_collectives(hlo_text: str):
    """[(op_name, groups_frozenset), ...] — one entry per defining
    collective instruction in the HLO module text."""
    out = []
    for line in hlo_text.splitlines():
        m = _DEF_RE.search(line)
        if not m or m.group(2) == "-done":
            continue
        gm = _GROUPS_ATTR_RE.search(line)
        out.append((m.group(1),
                    _parse_groups(gm.group(2)) if gm else frozenset()))
    return out


def _groups_match(observed: frozenset, expected: frozenset,
                  op: str) -> bool:
    if not observed:
        # a missing replica_groups attr means "all devices": accept only
        # when the axis group IS the whole mesh
        return len(expected) == 1
    if op == "collective-permute":
        # source_target_pairs: every (src, dst) must stay inside one
        # expected axis group
        return all(any(s in g and d in g for g in expected)
                   for s, d in observed)
    return observed == expected


# ---------------------------------------------------------------------------
# probes
# ---------------------------------------------------------------------------

def _build_mesh(dims: dict, devices=None):
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(int(dims.get(a, 1)) for a in MESH_AXES)
    world = int(np.prod(shape))
    if world > len(devices):
        raise ValueError(
            f"plan world {world} exceeds the {len(devices)} local "
            f"devices; validate on a matching test mesh (e.g. "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={world}) "
            f"or validate a same-shaped smaller plan")
    return Mesh(np.array(devices[:world]).reshape(shape), MESH_AXES)


def _compile_text(f, in_specs, out_spec, avals, mesh):
    import jax
    from jax.sharding import NamedSharding

    ns = [NamedSharding(mesh, s) for s in in_specs]
    out = NamedSharding(mesh, out_spec)
    return jax.jit(f, in_shardings=tuple(ns), out_shardings=out) \
        .lower(*avals).compile().as_text()


def _probe_mp(mesh, dims):
    """Column-parallel then row-parallel matmul: the partial sums the
    row contraction produces force exactly one all-reduce over mp."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    h = 16

    def f(x, w1, w2):
        y = jax.lax.with_sharding_constraint(
            x @ w1, NamedSharding(mesh, P(None, "mp")))
        return y @ w2

    avals = [jax.ShapeDtypeStruct((8, h), jnp.float32),
             jax.ShapeDtypeStruct((h, 4 * h), jnp.float32),
             jax.ShapeDtypeStruct((4 * h, h), jnp.float32)]
    txt = _compile_text(f, [P(), P(None, "mp"), P("mp", None)], P(),
                        avals, mesh)
    return txt, [("all-reduce", "mp", 1)]


def _probe_dp(mesh, dims):
    """Weight grad with the batch sharded over dp: the contraction over
    the sharded batch dim yields partials -> one all-reduce over dp."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    h = 16

    def f(x, w):
        return jax.grad(lambda w_: ((x @ w_) ** 2).sum())(w)

    avals = [jax.ShapeDtypeStruct((8, h), jnp.float32),
             jax.ShapeDtypeStruct((h, h), jnp.float32)]
    txt = _compile_text(f, [P("dp", None), P()], P(), avals, mesh)
    return txt, [("all-reduce", "dp", 1)]


def _probe_sharding_gather(mesh, dims):
    """ZeRO-3 forward: a dim-0-sharded parameter materialized replicated
    before use costs exactly one all-gather over the sharding axis. The
    replicated constraint pins the ZeRO semantics — without it GSPMD may
    legally prefer a partial-sum contraction (all-reduce) instead."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    h = 16

    def f(x, w):
        w_full = jax.lax.with_sharding_constraint(
            w, NamedSharding(mesh, P(None, None)))
        return x @ w_full

    avals = [jax.ShapeDtypeStruct((8, h), jnp.float32),
             jax.ShapeDtypeStruct((h, h), jnp.float32)]
    txt = _compile_text(f, [P(), P("sharding", None)], P(), avals, mesh)
    return txt, [("all-gather", "sharding", 1)]


def _probe_sharding_reduce(mesh, dims):
    """ZeRO-3 backward: batch sharded over the sharding axis, grad
    emitted in the param's dim-0 shards -> one reduce-scatter (XLA:CPU:
    all-reduce + slice — still one grad-reduce)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    h = 16

    def f(x, w):
        return jax.grad(lambda w_: ((x @ w_) ** 2).sum())(w)

    avals = [jax.ShapeDtypeStruct((8, h), jnp.float32),
             jax.ShapeDtypeStruct((h, h), jnp.float32)]
    txt = _compile_text(f, [P("sharding", None), P()],
                        P("sharding", None), avals, mesh)
    # the all-reduce+slice lowering renumbers shards with a
    # collective-permute — data movement inside the lowering, not an
    # extra reduction: allowed as a companion, never counted
    return txt, [("grad-reduce", "sharding", 1, ("collective-permute",))]


def _probe_sep(mesh, dims):
    """Ulysses boundary: reshard [b, s, heads, d] from seq-sharded to
    head-sharded over sep — one all-to-all (or its all-gather lowering)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sep = int(dims.get("sep", 1))
    heads = 2 * sep

    def f(x):
        return jax.lax.with_sharding_constraint(
            x * 1.0, NamedSharding(mesh, P(None, None, "sep", None)))

    avals = [jax.ShapeDtypeStruct((2, 4 * sep, heads, 8), jnp.float32)]
    txt = _compile_text(f, [P(None, "sep", None, None)],
                        P(None, None, "sep", None), avals, mesh)
    return txt, [("reshard", "sep", 1)]


def _probe_pp(mesh, dims):
    """Pipeline boundary: a ppermute ring over pp — one
    collective-permute whose source-target pairs stay inside pp groups."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    pp = int(dims.get("pp", 1))
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:                      # newer jax
        from jax import shard_map

    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def f(x):
        return shard_map(
            lambda t: jax.lax.ppermute(t, "pp", perm),
            mesh=mesh, in_specs=P("pp"), out_specs=P("pp"))(x)

    avals = [jax.ShapeDtypeStruct((8 * pp,), jnp.float32)]
    txt = _compile_text(f, [P("pp")], P("pp"), avals, mesh)
    return txt, [("permute", "pp", 1)]


_PROBES = (
    ("mp", "megatron-pair", _probe_mp),
    ("dp", "grad-allreduce", _probe_dp),
    ("sharding", "zero3-param-gather", _probe_sharding_gather),
    ("sharding", "zero3-grad-reduce", _probe_sharding_reduce),
    ("sep", "ulysses-reshard", _probe_sep),
    ("pp", "pipeline-permute", _probe_pp),
)


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

@dataclass
class ValidationReport:
    checks: list = field(default_factory=list)
    memory_ok: bool = True
    memory_detail: str = ""

    @property
    def ok(self) -> bool:
        return self.memory_ok and all(c["ok"] for c in self.checks)

    def failures(self) -> list:
        out = [c for c in self.checks if not c["ok"]]
        if not self.memory_ok:
            out.append({"probe": "memory-fit", "ok": False,
                        "detail": self.memory_detail})
        return out

    def to_dict(self) -> dict:
        return {"ok": self.ok, "memory_ok": self.memory_ok,
                "memory_detail": self.memory_detail,
                "checks": list(self.checks)}


def validate_plan(plan: Plan, devices=None) -> ValidationReport:
    """Prove a plan on the local test mesh. Compiles one probe per used
    parallel axis and counts collectives per (op-class, axis-group)
    against the prediction; re-asserts the memory-fit. Increments
    ``paddle_tpu_planner_validations_total{result=}``."""
    from .search import HBM_UTIL

    report = ValidationReport()
    dims = {a: plan.degree(a) for a in MESH_AXES}

    # memory-fit re-assertion (deserialized plans can't smuggle an OOM).
    # A bare probe plan (no topology, no predictions) has nothing to
    # verify; a plan that DOES carry either side but is missing the
    # other must FAIL — stripping the predicted block is exactly the
    # smuggling path this check closes.
    budget = plan.topology.get("hbm_bytes", 0)
    claimed = plan.predicted.get("per_chip_hbm_bytes", 0)
    if not plan.topology and not plan.predicted:
        report.memory_detail = "no memory claim (bare plan)"
    elif not (budget and claimed):
        report.memory_ok = False
        report.memory_detail = (
            f"unverifiable memory claim: per_chip_hbm_bytes={claimed!r}, "
            f"topology hbm_bytes={budget!r} (both required)")
    else:
        limit = budget * HBM_UTIL
        report.memory_ok = claimed <= limit
        report.memory_detail = (
            f"per-chip claim {claimed} vs budget {int(limit)} "
            f"({'fits' if report.memory_ok else 'DOES NOT FIT'})")

    active = [(axis, name, probe) for axis, name, probe in _PROBES
              if dims.get(axis, 1) > 1]
    if active:
        mesh = _build_mesh(dims, devices)
        for axis, name, probe in active:
            txt, expectations = probe(mesh, dims)
            found = count_hlo_collectives(txt)
            for exp in expectations:
                op_class, exp_axis, exp_count = exp[:3]
                allowed = exp[3] if len(exp) > 3 else ()
                accepted = OP_CLASSES[op_class]
                expected_groups = axis_groups(dims, exp_axis)
                hits = [
                    (op, g) for op, g in found
                    if op in accepted and
                    _groups_match(g, expected_groups, op)]
                # every collective in the probe must be accounted for:
                # extra instances on OTHER axes/ops are a model miss too
                # (minus declared lowering companions)
                extras = [(op, sorted(map(list, g))) for op, g in found
                          if (op, g) not in hits and op not in allowed]
                ok = len(hits) == exp_count and not extras
                report.checks.append({
                    "probe": name, "axis": exp_axis, "op": op_class,
                    "predicted": exp_count, "observed": len(hits),
                    "unexpected": extras, "ok": ok})

    from ..observability import metrics as m
    m.counter("paddle_tpu_planner_validations_total",
              "plan validations by result").inc(
        result="ok" if report.ok else "mismatch")
    return report
