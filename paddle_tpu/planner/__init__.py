"""`paddle.planner` — automatic parallelism planning (ROADMAP item 3).

One cost-modeled search turns ``(model, chip_count, topology)`` into a
complete, serializable :class:`Plan`: 5-D mesh shape (dp/pp/sharding/
sep/mp), per-layer PartitionSpecs (embedding / attention / MLP / head),
pipeline stage split + micro-batch count, and a recompute policy — with
DCN-awareness baked in (dp pinned to the slow axis; mp/sep must stay on
ICI).

The pipeline (docs/parallelism_planner.md):

* enumerate + prune with :mod:`paddle_tpu.auto_tuner`;
* score analytically with :mod:`paddle_tpu.cost_model.collective`
  alpha-beta formulas over the graph analyzer's per-op FLOPs and static
  peak-HBM (:class:`~.describe.ModelDesc`), rejecting memory-infeasible
  candidates BEFORE scoring;
* optionally refine the survivors with dry-run compiles or measured
  trials (:func:`refine_plans`);
* prove every emitted plan against compiled HLO on the test mesh
  (:func:`validate_plan` — exact per-(op, group) collective counts, the
  PR 6 proof machinery).

Apply with :func:`apply_plan` (fleet + PartitionSpecs in one call);
inspect from the shell with ``python -m paddle_tpu.planner``.
"""

from .describe import ModelDesc  # noqa: F401
from .plan import (Plan, SPEC_ROLES, active_plan, apply_plan,  # noqa: F401
                   build_specs)
from .refine import refine_plans  # noqa: F401
from .search import (PlannerResult, ScoredCandidate,  # noqa: F401
                     plan_search, predict_memory, predict_step_time)
from .topology import MESH_AXES, Topology  # noqa: F401
from .validate import (ValidationReport, axis_groups,  # noqa: F401
                       count_hlo_collectives, validate_plan)

__all__ = [
    "Topology", "ModelDesc", "Plan", "PlannerResult", "ScoredCandidate",
    "ValidationReport", "plan_search", "apply_plan", "validate_plan",
    "refine_plans", "active_plan", "build_specs", "predict_memory",
    "predict_step_time", "axis_groups", "count_hlo_collectives",
    "MESH_AXES", "SPEC_ROLES",
]
