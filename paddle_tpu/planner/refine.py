"""Optional refinement of the analytic ranking: dry-run compiles and
measured trials for the top-k plans (the auto_tuner's two trial modes,
driven by plans instead of bare candidates).

The analytic search orders hundreds of candidates in milliseconds; these
refiners spend real compile/execute time on the few survivors. The
caller supplies ``build(plan)`` returning ``(step_fn, args)`` — a real
train step on a model ALREADY configured for the plan (typically via
:func:`~.plan.apply_plan`); the refiner times it and re-ranks. The
topology is reset after every trial so plans cannot contaminate each
other (the ``measure_compiled_step`` contract).
"""

from __future__ import annotations

from ..auto_tuner.tuner import run_timed_trial

__all__ = ["refine_plans"]


def refine_plans(result, build, mode: str = "measured", top: int = 3,
                 steps: int = 3, warmup: int = 1):
    """Re-rank ``result.plans[:top]`` by real trials.

    ``mode="dryrun"`` runs exactly ONE step per plan (the compile +
    first dispatch — catches compile-time OOM and pathological lowering
    without burning steady-state time) and records
    ``predicted["dryrun_s"]``; ``mode="measured"`` runs ``warmup`` then
    ``steps`` timed steps and records ``predicted["measured_step_s"]``.
    Failing trials are recorded (``predicted["trial_error"]``) and sort
    last instead of killing the refinement. Returns the re-ranked plan
    list (also written back to ``result.plans``).
    """
    from ..distributed.topology import reset_topology_state

    if mode not in ("measured", "dryrun"):
        raise ValueError(f"mode must be 'measured' or 'dryrun', not "
                         f"{mode!r}")
    key = "measured_step_s" if mode == "measured" else "dryrun_s"
    trialed = []
    for p in list(result.plans[:max(top, 1)]):
        try:
            step, args = build(p)
            p.predicted[key] = run_timed_trial(
                step, args,
                steps=steps if mode == "measured" else 1,
                warmup=warmup if mode == "measured" else 0)
        except Exception as e:  # a failing trial never kills the search
            p.predicted["trial_error"] = f"{type(e).__name__}: {e}"
        finally:
            reset_topology_state()
        trialed.append(p)
    trialed.sort(key=lambda p: p.predicted.get(key, float("inf")))
    result.plans[:len(trialed)] = trialed
    return trialed
