"""The Plan: one serializable object holding every parallelism decision.

A plan answers the four questions the 5-D topology used to answer by
hand (ROADMAP item 3):

* **mesh** — degrees for the ``[dp, pp, sharding, sep, mp]`` axes (the
  fleet hybrid order; mp innermost so tensor-parallel traffic rides ICI
  neighbors);
* **specs** — per-parameter-role PartitionSpecs (embedding / attention /
  MLP / head) as ``regex pattern -> spec`` rows matched against
  ``named_parameters()`` names, covering both the GPT and Llama naming
  families;
* **schedule** — pipeline stage split + micro-batch count + schedule
  mode;
* **recompute** — whether activation recomputation is required to fit
  the per-chip HBM budget, and the policy.

``to_json``/``from_json`` round-trip the whole object (stable key order,
strict JSON); :func:`apply_plan` configures fleet + marks every parameter
spec in one call; :func:`plan_fingerprint` digests the decision fields
(not the predictions) so flight dumps can name the topology a process
died under.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field

from .topology import MESH_AXES

__all__ = ["Plan", "apply_plan", "active_plan", "SPEC_ROLES"]

PLAN_VERSION = 1

#: Role table: ``(name, pattern, spec builder)`` rows matched IN ORDER
#: against parameter names. Specs use the mesh axis names; ``None`` =
#: replicated dim. Covers both naming families:
#: GPT   — wte/wpe, blocks.N.attn.{qkv,proj}, blocks.N.mlp.{fc,proj}
#: Llama — embed_tokens, layers.N.self_attn.{q,k,v,o}_proj,
#:         layers.N.mlp.{gate,up,down}_proj, lm_head
SPEC_ROLES = (
    # vocab-parallel embedding: vocab dim over mp
    ("embedding", r"(^|\.)(wte|embed_tokens)\.weight$",
     lambda: ["mp", None]),
    # position embedding: replicated
    ("pos-embedding", r"(^|\.)wpe\.weight$", lambda: [None, None]),
    # column-parallel (out-dim sharded): qkv fusions, q/k/v, MLP up/gate/fc
    ("attention-qkv", r"(qkv|q_proj|k_proj|v_proj)\.weight$",
     lambda: [None, "mp"]),
    ("attention-qkv-bias", r"(qkv|q_proj|k_proj|v_proj)\.bias$",
     lambda: ["mp"]),
    ("mlp-in", r"(fc|gate_proj|up_proj)\.weight$", lambda: [None, "mp"]),
    ("mlp-in-bias", r"(fc|gate_proj|up_proj)\.bias$", lambda: ["mp"]),
    # row-parallel (in-dim sharded): attention out-proj, MLP down-proj
    ("attention-out", r"(attn\.proj|o_proj)\.weight$",
     lambda: ["mp", None]),
    ("mlp-out", r"(mlp\.proj|down_proj)\.weight$", lambda: ["mp", None]),
    # sharded LM head: vocab (out) dim over mp
    ("head", r"(^|\.)lm_head\.weight$", lambda: [None, "mp"]),
)


def build_specs(mp: int) -> dict:
    """The per-role spec table for an mp degree (empty when mp == 1:
    everything replicated, fleet's default annotation applies).

    The vocab-sharded roles (embedding, head) assume ``vocab % mp == 0``
    — the search guarantees it (``prune_by_divisibility`` rejects every
    mp that does not divide the vocab before a plan is built); callers
    constructing specs directly own that check.
    """
    if mp <= 1:
        return {}
    return {pattern: {"role": role, "spec": make()}
            for role, pattern, make in SPEC_ROLES}


@dataclass
class Plan:
    mesh: dict = field(default_factory=lambda: dict.fromkeys(MESH_AXES, 1))
    specs: dict = field(default_factory=dict)
    schedule: dict = field(default_factory=lambda: {
        "micro_batches": 1, "schedule_mode": "none", "stages": []})
    recompute: dict = field(default_factory=lambda: {
        "enable": False, "policy": "none"})
    global_batch: int = 1
    seq_len: int = 1
    model: dict = field(default_factory=dict)
    topology: dict = field(default_factory=dict)
    predicted: dict = field(default_factory=dict)
    version: int = PLAN_VERSION

    # -- queries ------------------------------------------------------------
    @property
    def world(self) -> int:
        n = 1
        for a in MESH_AXES:
            n *= int(self.mesh.get(a, 1))
        return n

    def degree(self, axis: str) -> int:
        return int(self.mesh.get(axis, 1))

    def mesh_shape(self) -> tuple:
        return tuple(int(self.mesh.get(a, 1)) for a in MESH_AXES)

    def spec_for(self, param_name: str):
        """PartitionSpec entry list for a parameter name, or None when no
        role matches (the parameter stays on fleet's default policy)."""
        for pattern, row in self.specs.items():
            if re.search(pattern, param_name):
                spec = row["spec"] if isinstance(row, dict) else row
                return [None if s is None else s for s in spec]
        return None

    def micro_batch_size(self) -> int:
        m = int(self.schedule.get("micro_batches", 1))
        return max(self.global_batch
                   // (self.degree("dp") * self.degree("sharding") * m), 1)

    def data_shards(self) -> int:
        """How many distinct input shards this plan's feeding needs: the
        dp and sharding axes both consume different batches; mp/pp/sep
        ranks replicate their dp rank's stream. This is the shard count
        ``paddle.io.ShardedDataset.from_plan`` deals the dataset into."""
        return max(self.degree("dp") * self.degree("sharding"), 1)

    def summary(self) -> str:
        d = self.mesh
        sched = self.schedule
        rc = "on" if self.recompute.get("enable") else "off"
        return (f"dp{d.get('dp', 1)} pp{d.get('pp', 1)} "
                f"sh{d.get('sharding', 1)} sep{d.get('sep', 1)} "
                f"mp{d.get('mp', 1)} "
                f"mb{sched.get('micro_batches', 1)} recompute={rc}")

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "mesh": {a: int(self.mesh.get(a, 1)) for a in MESH_AXES},
            "specs": self.specs,
            "schedule": self.schedule,
            "recompute": self.recompute,
            "global_batch": int(self.global_batch),
            "seq_len": int(self.seq_len),
            "model": self.model,
            "topology": self.topology,
            "predicted": self.predicted,
            "fingerprint": self.fingerprint(),
        }

    def to_json(self, indent: int | None = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        version = int(d.get("version", PLAN_VERSION))
        if version > PLAN_VERSION:
            raise ValueError(
                f"plan version {version} is newer than this build's "
                f"{PLAN_VERSION}")
        return cls(mesh=dict(d.get("mesh", {})),
                   specs=dict(d.get("specs", {})),
                   schedule=dict(d.get("schedule", {})),
                   recompute=dict(d.get("recompute", {})),
                   global_batch=int(d.get("global_batch", 1)),
                   seq_len=int(d.get("seq_len", 1)),
                   model=dict(d.get("model", {})),
                   topology=dict(d.get("topology", {})),
                   predicted=dict(d.get("predicted", {})),
                   version=version)

    @classmethod
    def from_json(cls, text: str) -> "Plan":
        return cls.from_dict(json.loads(text))

    def fingerprint(self) -> str:
        """Stable digest of the DECISION fields (mesh/specs/schedule/
        recompute/batch/seq + model and topology names) — predictions are
        excluded so re-scoring an identical plan can't change its id."""
        payload = json.dumps({
            "mesh": {a: int(self.mesh.get(a, 1)) for a in MESH_AXES},
            "specs": self.specs,
            "schedule": self.schedule,
            "recompute": self.recompute,
            "global_batch": int(self.global_batch),
            "seq_len": int(self.seq_len),
            "model": self.model.get("name", ""),
            "topology": (self.topology.get("name", ""),
                         self.topology.get("chips", 0)),
        }, sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# apply_plan: fleet + PartitionSpecs in one call
# ---------------------------------------------------------------------------

_ACTIVE: dict | None = None


def active_plan() -> dict | None:
    """{"fingerprint", "mesh", "summary"} of the last applied plan (flight
    dumps embed this so post-mortems name the topology they died under)."""
    return _ACTIVE


def apply_plan(model, plan: Plan, devices=None):
    """Configure fleet for ``plan`` and annotate ``model``'s parameters
    with the plan's PartitionSpecs — the one-call version of the manual
    ``DistributedStrategy`` + ``fleet.init`` + per-layer ``mark_sharding``
    recipe. Returns the fleet-wrapped model.

    Resets any previous topology first (a plan is a full replacement, not
    an overlay). ``pp > 1`` plans require a ``PipelineLayer`` model, the
    same contract ``fleet.distributed_model`` enforces.
    """
    global _ACTIVE
    from jax.sharding import PartitionSpec as P

    from ..distributed.fleet import DistributedStrategy, fleet
    from ..distributed.sharding_utils import mark_sharding
    from ..distributed.topology import reset_topology_state

    reset_topology_state()
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": plan.degree("dp"), "mp_degree": plan.degree("mp"),
        "pp_degree": plan.degree("pp"),
        "sharding_degree": plan.degree("sharding"),
        "sep_degree": plan.degree("sep")}
    strategy.pipeline_configs = {
        "accumulate_steps": int(plan.schedule.get("micro_batches", 1)),
        "micro_batch_size": plan.micro_batch_size()}
    if plan.degree("sharding") > 1:
        strategy.sharding = True
        strategy.sharding_configs = {
            "stage": 3, "degree": plan.degree("sharding")}
    if plan.recompute.get("enable"):
        strategy.recompute = True
        strategy.recompute_configs = {
            "enable": True,
            "policy": plan.recompute.get("policy", "full")}
    fleet.init(is_collective=True, strategy=strategy, devices=devices)

    for name, p in model.named_parameters():
        spec = plan.spec_for(name)
        if spec is not None:
            mark_sharding(p, P(*spec))
    wrapped = fleet.distributed_model(model)

    _ACTIVE = {"fingerprint": plan.fingerprint(),
               "mesh": {a: plan.degree(a) for a in MESH_AXES},
               "summary": plan.summary(),
               "data_shards": plan.data_shards()}
    from ..observability import metrics as _m
    _m.counter("paddle_tpu_planner_plans_applied_total",
               "plans applied via apply_plan").inc()
    try:
        from ..observability.flight import record as _flight_record
        _flight_record("plan_applied", fingerprint=_ACTIVE["fingerprint"],
                       summary=_ACTIVE["summary"])
    except Exception:
        pass
    return wrapped
