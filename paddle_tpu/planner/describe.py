"""ModelDesc: everything the planner's cost model needs to know about a
model, extracted from its config plus ONE abstract trace of its forward.

The trace rides the graph analyzer (:mod:`paddle_tpu.analysis.graph`):
``trace_layer`` binds parameters to tracers and abstract-evals the forward
+ loss on ``ShapeDtypeStruct`` avals — no device execution — and
``build_graph`` / ``peak_liveness`` turn the jaxpr into per-op FLOPs and
the static peak-HBM the memory-fit filter scales per candidate. This is
the same machinery PR 6 proved against compiled HLO, so the planner's
inputs are the analyzer's outputs, not hand-maintained formulas.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["ModelDesc"]


@dataclass
class ModelDesc:
    name: str
    num_layers: int
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    vocab_size: int
    ffn_size: int
    seq_len: int
    param_count: int
    param_bytes: int
    dtype_bytes: int = 4
    # analyzer-derived (per ONE sample at seq_len):
    flops_fwd_per_sample: float = 0.0
    act_peak_bytes_per_sample: int = 0

    @classmethod
    def from_model(cls, model, seq_len: int, name: str = "",
                   probe_batch: int = 2) -> "ModelDesc":
        """Extract the descriptor from a live ``nn.Layer`` whose config
        carries the transformer dims (GPTConfig / LlamaConfig shapes).

        The forward+loss is traced once at ``(probe_batch, seq_len)``
        avals; FLOPs and the liveness peak are divided back to
        per-sample so the search can scale them to any candidate's
        micro-batch size.
        """
        import jax
        import jax.numpy as jnp

        from ..analysis.graph.ir import build_graph
        from ..analysis.graph.liveness import peak_liveness
        from ..analysis.graph.trace import trace_layer

        cfg = getattr(model, "cfg", None)
        if cfg is None:
            raise TypeError(
                "ModelDesc.from_model needs a model with a .cfg carrying "
                "the transformer dims (GPT/Llama style); build a ModelDesc "
                "directly for custom models")
        num_layers = int(cfg.num_layers)
        hidden = int(cfg.hidden_size)
        heads = int(cfg.num_heads)
        kv_heads = int(getattr(cfg, "num_kv_heads", heads))
        vocab = int(cfg.vocab_size)
        ffn = int(getattr(cfg, "ffn_size", 0) or
                  getattr(cfg, "intermediate_size", 0) or 4 * hidden)
        seq_len = int(seq_len)
        if seq_len > int(cfg.max_position_embeddings):
            raise ValueError(
                f"seq_len {seq_len} exceeds the model's "
                f"max_position_embeddings {cfg.max_position_embeddings}")

        params = list(model.parameters())
        param_count = int(sum(p.size for p in params))
        param_bytes = int(sum(
            p.size * getattr(getattr(p, "_d", p), "dtype",
                             jnp.float32).itemsize for p in params))

        x = jax.ShapeDtypeStruct((probe_batch, seq_len), jnp.int32)
        y = jax.ShapeDtypeStruct((probe_batch, seq_len), jnp.int32)
        g = build_graph(trace_layer(model, x, labels=y),
                        name=name or type(model).__name__)
        live = peak_liveness(g)
        return cls(
            name=name or type(model).__name__,
            num_layers=num_layers, hidden_size=hidden, num_heads=heads,
            num_kv_heads=kv_heads, vocab_size=vocab, ffn_size=ffn,
            seq_len=seq_len, param_count=param_count,
            param_bytes=param_bytes,
            flops_fwd_per_sample=float(g.total_flops()) / probe_batch,
            act_peak_bytes_per_sample=int(
                live.intermediate_peak_bytes // probe_batch))

    def to_dict(self) -> dict:
        return {
            "name": self.name, "num_layers": self.num_layers,
            "hidden_size": self.hidden_size, "num_heads": self.num_heads,
            "num_kv_heads": self.num_kv_heads,
            "vocab_size": self.vocab_size, "ffn_size": self.ffn_size,
            "seq_len": self.seq_len, "param_count": self.param_count,
            "param_bytes": self.param_bytes,
            "dtype_bytes": self.dtype_bytes,
            "flops_fwd_per_sample": float(self.flops_fwd_per_sample),
            "act_peak_bytes_per_sample": int(
                self.act_peak_bytes_per_sample),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ModelDesc":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__
                      if k in d})
