"""nn.utils (reference: python/paddle/nn/utils/)."""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm", "bind_param_arrays"]


@contextlib.contextmanager
def bind_param_arrays(params, arrays):
    """Temporarily rebind each Parameter's storage to the given (usually
    traced) array, restoring the originals on exit. This is THE idiom for
    functionalizing framework modules into pure jax functions (used by the
    compiled pipeline, recompute, and the driver entry points) — a missed
    restore corrupts live params for the rest of the process, so every
    caller goes through this one context manager."""
    saved = [(p._d, p._node) for p in params]
    try:
        for p, a in zip(params, arrays):
            p._d = a
            p._node = None
        yield
    finally:
        for p, (d, n) in zip(params, saved):
            p._d = d
            p._node = n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(
            g._data.astype(jnp.float32)), norm_type)) for g in grads),
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad._data = p._grad._data * scale.astype(p._grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p._grad is not None:
            p._grad._data = jnp.clip(p._grad._data, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None) -> Tensor:
    return Tensor(jnp.concatenate([p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._data = vec._data[offset:offset + n].reshape(p._data.shape) \
            .astype(p._data.dtype)
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize ``name`` as g * v/||v|| (reference: nn/utils/weight_norm_hook.py)."""
    w = getattr(layer, name)
    axes = tuple(i for i in range(w.ndim) if i != dim)
    g = jnp.sqrt(jnp.sum(jnp.square(w._data), axis=axes, keepdims=True))
    from ...core.tensor import Parameter
    layer.add_parameter(name + "_g", Parameter(g))
    layer.add_parameter(name + "_v", Parameter(w._data))
    del layer._parameters[name]

    def hook(l, inputs):
        v = l._parameters[name + "_v"]
        gg = l._parameters[name + "_g"]
        norm = jnp.sqrt(jnp.sum(jnp.square(v._data), axis=axes, keepdims=True))
        from ...autograd.function import apply
        wt = apply(lambda vv, ggg: ggg * vv / jnp.maximum(
            jnp.sqrt(jnp.sum(jnp.square(vv), axis=axes, keepdims=True)), 1e-12),
            v, gg, name="weight_norm")
        object.__setattr__(l, "_wn_" + name, wt)
        l.__dict__[name] = wt
        return None
    layer._wn_hook = layer.register_forward_pre_hook(hook)
    layer._wn_name = name
    return layer


def remove_weight_norm(layer, name="weight"):
    from ...core.tensor import Parameter
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    axes_norm = jnp.sqrt(jnp.sum(jnp.square(v._data),
                                 axis=tuple(range(1, v.ndim)), keepdims=True))
    layer._wn_hook.remove()
    layer.__dict__.pop(name, None)
    w = g._data * v._data / jnp.maximum(
        jnp.sqrt(jnp.sum(jnp.square(v._data),
                         axis=tuple(i for i in range(v.ndim) if g._data.shape[i] == 1
                                    ) or (0,), keepdims=True)), 1e-12)
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """Reparametrize ``name`` as W / sigma_max(W), sigma estimated by power
    iteration with persistent u/v vectors refreshed every forward
    (reference: python/paddle/nn/utils/spectral_norm_hook.py). The u/v
    estimates are constants w.r.t. autograd (stop-gradient, as in the
    reference); sigma itself stays in the graph so d(W/sigma)/dW is exact
    for the current estimate."""
    import numpy as np
    from ...core.tensor import Parameter
    from ...autograd.function import apply

    w = getattr(layer, name)
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)

    def as_mat(arr):
        return jnp.transpose(arr, perm).reshape(arr.shape[dim], -1)

    h, cols = as_mat(w._data).shape
    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(h).astype(np.float32)
    layer._sn_u = jnp.asarray(u0 / max(np.linalg.norm(u0), eps))
    layer.add_parameter(name + "_orig", Parameter(w._data))
    del layer._parameters[name]

    def _normalize(x):
        return x / jnp.maximum(jnp.linalg.norm(x), eps)

    def hook(l, inputs):
        from ...jit.api import in_to_static_trace
        w_orig = l._parameters[name + "_orig"]
        wm = as_mat(w_orig._data)
        u = l._sn_u
        for _ in range(max(n_power_iterations, 1)):
            v = _normalize(wm.T @ u)
            u = _normalize(wm @ v)
        if not in_to_static_trace():
            # persist the refreshed estimate only when it is a concrete
            # array — storing a trace-time tracer on the layer would poison
            # later eager forwards (UnexpectedTracerError)
            l._sn_u = jax.lax.stop_gradient(u)
        uc, vc = jax.lax.stop_gradient(u), jax.lax.stop_gradient(v)

        def f(ww):
            sigma = uc @ (as_mat(ww) @ vc)
            return ww / jnp.maximum(sigma, eps)
        wt = apply(f, w_orig, name="spectral_norm")
        l.__dict__[name] = wt
        return None

    layer._sn_hook = layer.register_forward_pre_hook(hook)
    layer._sn_name = name
    return layer
