"""`paddle.nn` equivalent (reference: python/paddle/nn/__init__.py)."""

from .layer import Layer  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layers.common import *  # noqa: F401,F403
from .layers.norm import *  # noqa: F401,F403
from .layers.container import *  # noqa: F401,F403
from .layers.activation import *  # noqa: F401,F403
from .layers.conv import *  # noqa: F401,F403
from .layers.loss import *  # noqa: F401,F403
from .layers.transformer import *  # noqa: F401,F403
from .layers.pooling import *  # noqa: F401,F403
from .layers.rnn import *  # noqa: F401,F403
from .decode import Decoder, BeamSearchDecoder, dynamic_decode  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .utils import clip_grad_norm_, clip_grad_value_, parameters_to_vector, vector_to_parameters  # noqa: F401
