"""Decoding API: BeamSearchDecoder + dynamic_decode (reference:
python/paddle/nn/decode.py — Decoder :60, BeamSearchDecoder :153,
dynamic_decode :994).

TPU-native redesign: the reference drives `decoder.step` from an imperative
Python loop (`_dynamic_decode_imperative`, decode.py:686) growing Python
lists. Here step 0 runs once to discover the output structure, then the
remaining steps run inside ONE `lax.while_loop` with preallocated
[T, ...] output buffers. In eager mode the loop exits early once all rows
finish and the result is sliced to the actually-decoded length (matching
the reference's dynamic output length); under `jit`/`to_static` the
compiled loop runs all T steps — all-finished beam search is a fixed point
(finished beams re-emit end_token with parent=identity), so the tail steps
are exact rather than zero-garbage that would corrupt gather_tree's
backtrace. Decoding is a no-grad path (the reference's beam top-k has no
gradient either).
"""

from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..autograd.grad_mode import no_grad

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]

_KINF = 1e9


def _arr(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _is_tensor(v):
    return isinstance(v, Tensor)


def _flatten(struct):
    return jax.tree_util.tree_flatten(struct, is_leaf=_is_tensor)


def _to_arrays(flat):
    return [_arr(t) for t in flat]


def _wrap(tdef, arrays):
    return jax.tree_util.tree_unflatten(tdef, [Tensor(a) for a in arrays])


class Decoder:
    """Abstract decode-step interface (reference decode.py:60)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a step cell (reference decode.py:153). The cell's
    inputs/states ride merged [batch*beam, ...] shapes through the cell and
    split back to [batch, beam, ...] for scoring."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] with each row repeated beam times
        (reference decode.py:478) — for tensors used inside `cell.call`
        such as attention memory."""
        a = _arr(x)
        return Tensor(jnp.repeat(a, beam_size, axis=0))

    def _expand(self, t):
        a = _arr(t)
        return jnp.repeat(a[:, None], self.beam_size, axis=1)

    def _merge(self, a):
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a):
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    def initialize(self, initial_cell_states):
        flat, tdef = _flatten(initial_cell_states)
        batch = _arr(flat[0]).shape[0]
        cell_states = jax.tree_util.tree_unflatten(
            tdef, [Tensor(self._expand(t)) for t in flat])
        init_ids = jnp.full((batch, self.beam_size), self.start_token,
                            jnp.int64)
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-_KINF] * (self.beam_size - 1)],
                        jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), jnp.bool_)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int64)
        inputs = Tensor(init_ids)
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        state = self.StateWrapper(cell_states, Tensor(log_probs),
                                  Tensor(finished), Tensor(lengths))
        return inputs, state, Tensor(finished)

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        lg = _arr(logits).astype(jnp.float32)       # [B, K, V]
        b, k, v = lg.shape
        step_lp = jax.nn.log_softmax(lg, axis=-1)
        # finished beams may only extend with end_token (score 0)
        noend = jnp.full((v,), -_KINF, jnp.float32).at[self.end_token].set(0.0)
        fin = _arr(beam_state.finished)
        step_lp = jnp.where(fin[:, :, None], noend[None, None, :], step_lp)
        log_probs = step_lp + _arr(beam_state.log_probs)[:, :, None]
        scores = log_probs.reshape(b, k * v)
        topk_scores, topk_idx = jax.lax.top_k(scores, k)
        beam_idx = (topk_idx // v).astype(jnp.int64)     # [B, K]
        token_idx = (topk_idx % v).astype(jnp.int64)
        b_rows = jnp.arange(b)[:, None]
        next_lp = scores[b_rows, topk_idx]

        def regather(t):
            return Tensor(_arr(t)[b_rows, beam_idx])

        cell_states = jax.tree_util.tree_map(
            regather, next_cell_states, is_leaf=_is_tensor)
        next_fin = fin[b_rows, beam_idx]
        next_len = _arr(beam_state.lengths)[b_rows, beam_idx]
        next_len = next_len + (~next_fin).astype(jnp.int64)
        next_fin = next_fin | (token_idx == self.end_token)

        out = self.OutputWrapper(Tensor(topk_scores), Tensor(token_idx),
                                 Tensor(beam_idx))
        st = self.StateWrapper(cell_states, Tensor(next_lp),
                               Tensor(next_fin), Tensor(next_len))
        return out, st

    def step(self, time, inputs, states, **kwargs):
        merged_in = jax.tree_util.tree_map(
            lambda t: Tensor(self._merge(_arr(t))), inputs,
            is_leaf=_is_tensor)
        merged_states = jax.tree_util.tree_map(
            lambda t: Tensor(self._merge(_arr(t))), states.cell_states,
            is_leaf=_is_tensor)
        cell_out, next_cell_states = self.cell(merged_in, merged_states,
                                               **kwargs)
        cell_out = jax.tree_util.tree_map(
            lambda t: Tensor(self._split(_arr(t))), cell_out,
            is_leaf=_is_tensor)
        next_cell_states = jax.tree_util.tree_map(
            lambda t: Tensor(self._split(_arr(t))), next_cell_states,
            is_leaf=_is_tensor)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        out, st = self._beam_search_step(time, cell_out, next_cell_states,
                                         states)
        ids = out.predicted_ids
        next_inputs = self.embedding_fn(ids) if self.embedding_fn else ids
        return out, st, next_inputs, st.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        from . import functional as F
        predicted_ids = F.gather_tree(outputs.predicted_ids,
                                      outputs.parent_ids)
        return predicted_ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Repeat `decoder.step` until all finished or max_step_num is reached
    (reference decode.py:994; runs max_step_num + 1 steps like the
    reference's `step_idx > max_step_num` break)."""
    if max_step_num is None:
        raise ValueError(
            "dynamic_decode requires max_step_num on this backend: the "
            "compiled decode preallocates [T, ...] output buffers")
    t_total = int(max_step_num) + 1

    with no_grad():
        inputs, states, finished = decoder.initialize(inits)
        seq_len0 = jnp.zeros_like(_arr(finished), jnp.int64)

        # step 0 outside the loop discovers the output structure
        out0, states, inputs, next_fin = decoder.step(
            Tensor(jnp.zeros((1,), jnp.int64)), inputs, states, **kwargs)
        if decoder.tracks_own_finished:
            finished = next_fin
            seq_len = getattr(states, "lengths", None)
            seq_len = _arr(seq_len) if seq_len is not None else seq_len0
        else:
            finished = Tensor(_arr(next_fin) | _arr(finished))
            # reference decode.py:728: += ~finished AFTER the or-update
            seq_len = seq_len0 + (~_arr(finished)).astype(jnp.int64)

        out_flat0, out_def = _flatten(out0)
        out_arr0 = _to_arrays(out_flat0)
        bufs = tuple(
            jnp.zeros((t_total,) + a.shape, a.dtype).at[0].set(a)
            for a in out_arr0)

        st_flat0, st_def = _flatten(states)
        in_flat0, in_def = _flatten(inputs)

        def pack(t, inputs_a, states_a, fin_a, slen, bufs):
            return (jnp.asarray(t, jnp.int64), tuple(inputs_a),
                    tuple(states_a), fin_a, slen, bufs)

        carry0 = pack(1, _to_arrays(in_flat0), _to_arrays(st_flat0),
                      _arr(finished), seq_len, bufs)

        traced = any(isinstance(a, jax.core.Tracer)
                     for a in (_arr(finished),) + tuple(_to_arrays(st_flat0)))

        def cond_fn(c):
            t, _, _, fin, _, _ = c
            if traced:
                # compiled path runs ALL steps: with static [T, ...] buffers
                # an early exit would leave a zero-filled tail that corrupts
                # finalize (gather_tree backtracks through zero parent_ids).
                # All-finished decoding is a fixed point — finished beams
                # re-emit end_token with parent=identity and unchanged
                # scores/lengths — so the extra steps are exact, and eos
                # masking inside decoder.step keeps them cheap for XLA.
                return t < t_total
            return (t < t_total) & ~jnp.all(fin)

        def body_fn(c):
            t, in_a, st_a, fin, slen, bufs = c
            states_t = _wrap(st_def, st_a)
            inputs_t = _wrap(in_def, in_a)
            out, nstates, ninputs, nfin = decoder.step(
                Tensor(t.reshape(1)), inputs_t, states_t, **kwargs)
            if decoder.tracks_own_finished:
                fin2 = _arr(nfin)
                nlen = getattr(nstates, "lengths", None)
                slen2 = _arr(nlen) if nlen is not None else slen
            else:
                fin2 = _arr(nfin) | fin
                slen2 = slen + (~fin2).astype(jnp.int64)
                if impute_finished:  # keep old states for finished rows
                    old_flat, _ = _flatten(states_t)
                    new_flat, ndef = _flatten(nstates)
                    kept = []
                    for o, n in zip(old_flat, new_flat):
                        oa, na = _arr(o), _arr(n)
                        m = fin.reshape(fin.shape + (1,) * (na.ndim - fin.ndim))
                        kept.append(jnp.where(m, oa, na))
                    nstates = _wrap(ndef, kept)
            o_flat, _ = _flatten(out)
            o_arr = _to_arrays(o_flat)
            bufs2 = tuple(
                jax.lax.dynamic_update_index_in_dim(bf, a, t, 0)
                for bf, a in zip(bufs, o_arr))
            n_flat, _ = _flatten(nstates)
            i_flat, _ = _flatten(ninputs)
            return pack(t + 1, _to_arrays(i_flat), _to_arrays(n_flat),
                        fin2, slen2, bufs2)

        t_f, _, st_f, fin_f, slen_f, bufs_f = jax.lax.while_loop(
            cond_fn, body_fn, carry0)

        concrete = not isinstance(t_f, jax.core.Tracer)
        if concrete:  # eager: slice to the actually-decoded length
            n = int(t_f)
            bufs_f = tuple(b[:n] for b in bufs_f)
        outputs = _wrap(out_def, bufs_f)          # time-major [T, ...]
        final_states = _wrap(st_def, st_f)
        seq_lengths = Tensor(slen_f)

        try:
            outputs, final_states = decoder.finalize(outputs, final_states,
                                                     seq_lengths)
        except NotImplementedError:
            pass

        if not output_time_major:
            outputs = jax.tree_util.tree_map(
                lambda t: Tensor(jnp.swapaxes(_arr(t), 0, 1)), outputs,
                is_leaf=_is_tensor)

    if return_length:
        return outputs, final_states, seq_lengths
    return outputs, final_states
