"""Gradient clipping (reference: python/paddle/nn/clip.py).

Clip objects are attached to optimizers via ``grad_clip=`` and applied to the
(param, grad) list before the update, exactly like the reference.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale.astype(g._data.dtype)))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip (reference: ClipGradByGlobalNorm; the hybrid-parallel
    variant lives in distributed.fleet HybridParallelClipGrad)."""

    #: the global norm the most recent __call__ computed — a concrete device
    #: scalar after an eager step (the fused program returns it explicitly),
    #: a tracer mid-trace, None before any call / when nothing was clipped.
    #: HealthMonitor reads this instead of running a second device reduction.
    last_global_norm = None

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                continue
            sq.append(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
        if not sq:
            self.last_global_norm = None
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        self.last_global_norm = global_norm
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                # need_clip=False grads are left untouched (reference
                # behavior: excluded from the norm AND from the scaling)
                out.append((p, g))
            else:
                out.append((p, Tensor(g._data * scale.astype(g._data.dtype))))
        return out
