"""Weight initializers (reference: python/paddle/nn/initializer/).

Each initializer is a callable ``(shape, dtype) -> jax.Array`` drawing from the
global generator, and doubles as the `paddle.nn.initializer.*` API surface.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core import generator as gen_mod

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "Bilinear",
    "set_global_initializer",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv_transpose1d": 1.0, "conv_transpose2d": 1.0,
        "conv_transpose3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return gains[nonlinearity]


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # linear weight stored [in, out] (paddle layout)
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None) -> jax.Array:
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value,
                        dtypes.dtype_from_any(dtype).np_dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        key = gen_mod.default_generator.split()
        dt = dtypes.dtype_from_any(dtype).np_dtype
        return self.mean + self.std * jax.random.normal(key, tuple(shape), dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=None):
        key = gen_mod.default_generator.split()
        dt = dtypes.dtype_from_any(dtype).np_dtype
        return self.mean + self.std * jax.random.truncated_normal(
            key, self.a, self.b, tuple(shape), dt)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        key = gen_mod.default_generator.split()
        dt = dtypes.dtype_from_any(dtype).np_dtype
        return jax.random.uniform(key, tuple(shape), dt, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fin, fout = _fans(tuple(shape))
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        std = self.gain * math.sqrt(2.0 / (fin + fout))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fin, fout = _fans(tuple(shape))
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        limit = self.gain * math.sqrt(6.0 / (fin + fout))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        fin, _ = _fans(tuple(shape))
        fin = self.fan_in if self.fan_in is not None else fin
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fin)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        fin, _ = _fans(tuple(shape))
        fin = self.fan_in if self.fan_in is not None else fin
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fin)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        from ..core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(np.asarray(v) if not isinstance(v, jax.Array) else v)
        dt = dtypes.dtype_from_any(dtype).np_dtype
        return arr.reshape(tuple(shape)).astype(dt)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        key = gen_mod.default_generator.split()
        dt = dtypes.dtype_from_any(dtype).np_dtype
        return self.gain * jax.nn.initializers.orthogonal()(key, tuple(shape), dt)


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dt = dtypes.dtype_from_any(dtype).np_dtype
        arr = np.zeros(tuple(shape), dt)
        out_c, in_c = shape[0], shape[1]
        per = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per, in_c)):
                center = tuple(s // 2 for s in shape[2:])
                arr[(g * per + i, i) + center] = 1.0
        return jnp.asarray(arr)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convolutions
    (reference: nn/initializer/Bilinear.py:26): weight [C_out, C_in, K, K]
    gets the separable triangle kernel so conv_transpose with stride f and
    kernel 2f-f%2 performs bilinear upsampling out of the box."""

    def __call__(self, shape, dtype=None):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight, got "
                             f"{shape}")
        k = shape[-1]
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = jnp.ogrid[:k, :k]
        filt = ((1 - jnp.abs(og[0] / f - c))
                * (1 - jnp.abs(og[1] / f - c)))       # [K, K]
        w = jnp.broadcast_to(filt, tuple(shape))
        return w.astype(dtypes.dtype_from_any(dtype).np_dtype)


_GLOBAL_INITIALIZER: list = [None, None]  # [weight_init, bias_init]


def set_global_initializer(weight_init, bias_init=None):
    """Override the default initializers used when a ParamAttr carries
    none (reference: nn/initializer/__init__.py set_global_initializer;
    pass None, None to restore the framework defaults)."""
    _GLOBAL_INITIALIZER[0] = weight_init
    _GLOBAL_INITIALIZER[1] = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_INITIALIZER[1 if is_bias else 0]


# reference nn/initializer/lazy_init.py exposes LazyGuard at this path
import types as _types  # noqa: E402

from ..framework.parameter import LazyGuard  # noqa: E402,F401

lazy_init = _types.SimpleNamespace(LazyGuard=LazyGuard)
