"""`paddle.nn.quant` (reference: python/paddle/nn/quant/ — quant layer
surface: Stub, quant/dequant helpers, weight-only linear). The actual
quantization machinery lives in paddle_tpu.quantization; this namespace is
the layer-level entry the reference exposes."""

from __future__ import annotations

from ...quantization.quanters import FakeQuanterWithAbsMaxObserver  # noqa: F401
from ...quantization.wrapper import Int8WeightOnlyLinear, QuantedLinear  # noqa: F401
from ...quantization.functional import (  # noqa: F401
    absmax_scale,
    dequant_matmul_int8,
    fake_quant,
    quantize_weight_int8,
)
from . import quant_layers  # noqa: F401
from .quant_layers import (  # noqa: F401
    FakeQuantAbsMax, FakeQuantChannelWiseAbsMax, FakeQuantMAOutputScaleLayer,
    FakeQuantMovingAverageAbsMax, MAOutputScaleLayer,
    MovingAverageAbsMaxScale, QuantizedColumnParallelLinear, QuantizedConv2D,
    QuantizedConv2DTranspose, QuantizedLinear, QuantizedMatmul,
    QuantizedRowParallelLinear)
from .quantized_linear import (  # noqa: F401
    llm_int8_linear,
    weight_dequantize,
    weight_only_linear,
    weight_quantize,
)
from ..layer import Layer

__all__ = ['Stub', 'QuantStub', 'weight_quantize', 'fake_quant', 'llm_int8_linear',
           'weight_dequantize', 'weight_only_linear',
           'absmax_scale', 'dequant_matmul_int8', 'quantize_weight_int8',
           'QuantedLinear', 'Int8WeightOnlyLinear',
           'FakeQuanterWithAbsMaxObserver', 'quant_layers']


class Stub(Layer):
    """Observer insertion point (reference nn/quant/stub.py Stub): identity
    in float graphs; the QAT pass replaces it with the configured quanter."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x


QuantStub = Stub


def quanted_layer_types():
    """Layer classes produced by quantization wrapping."""
    return [QuantedLinear, Int8WeightOnlyLinear]
