"""QAT fake-quant layers (reference: python/paddle/nn/quant/
quant_layers.py — FakeQuantAbsMax :51, FakeQuantMovingAverageAbsMax :152,
FakeQuantChannelWiseAbsMax :285, MovingAverageAbsMaxScale :393,
QuantizedConv2D :509, QuantizedConv2DTranspose :~620, QuantizedLinear
:726, QuantizedColumnParallelLinear / QuantizedRowParallelLinear,
QuantizedMatmul, MAOutputScaleLayer, FakeQuantMAOutputScaleLayer).

All quant-dequant runs with a straight-through estimator
(quantization/functional.fake_quant_array), so these layers train inside
jitted steps; the moving-average scale state updates functionally."""

from __future__ import annotations

import jax.numpy as jnp

from ...autograd.function import apply
from ...quantization.functional import absmax_scale, fake_quant_array
from ..layer import Layer

__all__ = [
    "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
    "FakeQuantChannelWiseAbsMax", "FakeQuantMAOutputScaleLayer",
    "MAOutputScaleLayer", "MovingAverageAbsMaxScale", "QuantizedConv2D",
    "QuantizedConv2DTranspose", "QuantizedLinear", "QuantizedMatmul",
    "QuantizedColumnParallelLinear", "QuantizedRowParallelLinear",
]


class FakeQuantAbsMax(Layer):
    """Per-tensor absmax quant-dequant (reference quant_layers.py:51)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32",
                 quant_on_weight=False, reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, x):
        def f(a):
            return fake_quant_array(a, absmax_scale(a), self._quant_bits)
        return apply(f, x, name="fake_quant_abs_max")


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-channel absmax quant-dequant (reference quant_layers.py:285)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32", quant_on_weight=False,
                 reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._quant_axis = quant_axis

    def forward(self, x):
        ax = self._quant_axis

        def f(a):
            scale = absmax_scale(a, axis=ax)
            shape = [1] * a.ndim
            shape[ax] = -1
            return fake_quant_array(a, scale.reshape(shape),
                                    self._quant_bits)
        return apply(f, x, name="fake_quant_channel_wise_abs_max")


class _MovingScale(Layer):
    """Shared moving-average absmax scale state:
    scale = (r*accum + max|x|) / (r*state + 1) (reference :157)."""

    def __init__(self, moving_rate=0.9):
        super().__init__()
        import paddle_tpu as paddle
        self._moving_rate = moving_rate
        self._accum = paddle.to_tensor(jnp.zeros((), jnp.float32))
        self._state = paddle.to_tensor(jnp.zeros((), jnp.float32))

    def update(self, x):
        r = self._moving_rate
        cur = x.abs().max().cast("float32")
        if self.training:
            new_accum = apply(lambda a, c: r * a + c, self._accum, cur,
                              name="ma_scale_accum")
            new_state = apply(lambda s: r * s + 1.0, self._state,
                              name="ma_scale_state")
            self._accum._d = new_accum._d
            self._state._d = new_state._d
        scale = apply(
            lambda a, s: jnp.where(s > 0, a / jnp.maximum(s, 1e-9),
                                   jnp.ones((), jnp.float32)),
            self._accum, self._state, name="ma_scale")
        return scale

    @property
    def scale(self):
        import paddle_tpu as paddle
        return paddle.to_tensor(
            self._accum._d / jnp.maximum(self._state._d, 1e-9))


class FakeQuantMovingAverageAbsMax(Layer):
    """Reference quant_layers.py:152."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32", reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._ma = _MovingScale(moving_rate)

    def forward(self, x):
        scale = self._ma.update(x)
        bits = self._quant_bits
        return apply(lambda a, s: fake_quant_array(a, s, bits), x, scale,
                     name="fake_quant_moving_average_abs_max")


class MovingAverageAbsMaxScale(Layer):
    """Maintains the output scale only; x passes through (reference
    quant_layers.py:393)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32",
                 reduce_type=None):
        super().__init__()
        self._ma = _MovingScale(moving_rate)

    @property
    def scale(self):
        return self._ma.scale

    def forward(self, x):
        self._ma.update(x)
        return x


class MAOutputScaleLayer(Layer):
    """Wrap a layer, tracking its output scale (reference
    quant_layers.py MAOutputScaleLayer)."""

    def __init__(self, layer=None, moving_rate=0.9, name=None,
                 dtype="float32", reduce_type=None):
        super().__init__()
        self._layer = layer
        self._ma_output_scale = MovingAverageAbsMaxScale(
            name, moving_rate, dtype)

    def forward(self, *args, **kwargs):
        out = self._layer(*args, **kwargs)
        if isinstance(out, (list, tuple)):
            return out
        return self._ma_output_scale(out)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer, fake-quantizing its output with a moving-average
    scale (reference quant_layers.py FakeQuantMAOutputScaleLayer)."""

    def __init__(self, layer=None, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, name=None, reduce_type=None, *args,
                 **kwargs):
        super().__init__()
        self._layer = layer
        self._fake_quant_output = FakeQuantMovingAverageAbsMax(
            name, moving_rate, quant_bits=activation_bits)

    def forward(self, *args, **kwargs):
        out = self._layer(*args, **kwargs)
        if isinstance(out, (list, tuple)):
            return out
        return self._fake_quant_output(out)


def _make_weight_quanter(weight_quantize_type, weight_bits, quant_axis=0):
    if weight_quantize_type == "channel_wise_abs_max":
        return FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                          quant_axis=quant_axis)
    if weight_quantize_type == "moving_average_abs_max":
        return FakeQuantMovingAverageAbsMax(quant_bits=weight_bits)
    return FakeQuantAbsMax(quant_bits=weight_bits, quant_on_weight=True)


class _QuantizedWrapper(Layer):
    """Common: fake-quant activation + weight, call the float layer's
    functional body with the quantized pair (the reference Quantized*
    classes follow exactly this shape)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_quant_axis=0, **kwargs):
        super().__init__()
        self._layer = layer
        self.weight = getattr(layer, "weight", None)
        self.bias = getattr(layer, "bias", None)
        if activation_quantize_type == "moving_average_abs_max":
            self._fake_quant_input = FakeQuantMovingAverageAbsMax(
                moving_rate=moving_rate, quant_bits=activation_bits)
        else:
            self._fake_quant_input = FakeQuantAbsMax(
                quant_bits=activation_bits)
        self._fake_quant_weight = _make_weight_quanter(
            weight_quantize_type, weight_bits, weight_quant_axis)

    def _quant_pair(self, x):
        qx = self._fake_quant_input(x)
        qw = self._fake_quant_weight(self.weight)
        return qx, qw


class QuantizedConv2D(_QuantizedWrapper):
    """Reference quant_layers.py:509."""

    def forward(self, x):
        from .. import functional as F
        qx, qw = self._quant_pair(x)
        lay = self._layer
        return F.conv2d(qx, qw, bias=self.bias,
                        stride=getattr(lay, "_stride", 1),
                        padding=getattr(lay, "_padding", 0),
                        dilation=getattr(lay, "_dilation", 1),
                        groups=getattr(lay, "_groups", 1),
                        data_format=getattr(lay, "_data_format", "NCHW"))


class QuantizedConv2DTranspose(_QuantizedWrapper):
    """Reference quant_layers.py QuantizedConv2DTranspose."""

    def forward(self, x, output_size=None):
        from .. import functional as F
        qx, qw = self._quant_pair(x)
        lay = self._layer
        return F.conv2d_transpose(
            qx, qw, bias=self.bias, stride=getattr(lay, "_stride", 1),
            padding=getattr(lay, "_padding", 0),
            dilation=getattr(lay, "_dilation", 1),
            groups=getattr(lay, "_groups", 1), output_size=output_size,
            data_format=getattr(lay, "_data_format", "NCHW"))


class QuantizedLinear(_QuantizedWrapper):
    """Reference quant_layers.py:726."""

    def forward(self, x):
        from .. import functional as F
        qx, qw = self._quant_pair(x)
        return F.linear(qx, qw, self.bias)


class QuantizedMatmul(Layer):
    """Reference quant_layers.py QuantizedMatmul: fake-quant both matmul
    operands."""

    def __init__(self, layer=None, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kwargs):
        super().__init__()
        self._fake_quant_x = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)
        self._fake_quant_y = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x, y, transpose_x=False, transpose_y=False,
                name=None):
        import paddle_tpu as paddle
        return paddle.matmul(self._fake_quant_x(x), self._fake_quant_y(y),
                             transpose_x, transpose_y)


class QuantizedColumnParallelLinear(_QuantizedWrapper):
    """Reference quant_layers.py QuantizedColumnParallelLinear: quantize
    then run the column-parallel body (gather stays fp32)."""

    def forward(self, x):
        qx, qw = self._quant_pair(x)
        lay = self._layer
        saved_w = lay.weight
        try:
            lay.weight = qw
            return lay.forward(qx)
        finally:
            lay.weight = saved_w


class QuantizedRowParallelLinear(QuantizedColumnParallelLinear):
    """Reference quant_layers.py QuantizedRowParallelLinear."""
