"""Weight-only quantized linear (reference:
python/paddle/nn/quant/quantized_linear.py:25 `weight_quantize`, :70
`weight_dequantize`, :116 `weight_only_linear` — CUDA weight-only GEMM).

TPU mapping: int8 weights feed the fused Pallas weight-only matmul
(ops/kernels/wo_matmul_pallas.py — in-core dequant, halved HBM weight
traffic). int4 stores two nibbles per int8 byte (half the HBM footprint);
the unpack runs as XLA ops in front of the same kernel.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...autograd.function import apply, apply_multi
from ...quantization.functional import dequant_matmul_int8, \
    quantize_weight_int8

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear"]

_ALGOS = ("weight_only_int8", "weight_only_int4")


def _check_algo(algo):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r} "
                         f"(llm.int8 needs activation stats; use the "
                         f"quantization PTQ flow)")


def _pack_int4(q):
    """[K, N] int4 values in [-7, 7] -> [K, ceil(N/2)] bytes (two nibbles,
    low nibble = even column)."""
    n = q.shape[1]
    if n % 2:
        q = jnp.pad(q, ((0, 0), (0, 1)))
    lo = q[:, 0::2].astype(jnp.int32) & 0xF
    hi = q[:, 1::2].astype(jnp.int32) & 0xF
    return (lo | (hi << 4)).astype(jnp.int8)


def _unpack_int4(packed, n):
    """Inverse of _pack_int4: [K, ceil(N/2)] bytes -> [K, N] int8 in
    [-7, 7] (sign-extend each nibble)."""
    b = packed.astype(jnp.int32)
    lo = (b & 0xF).astype(jnp.int8)
    hi = ((b >> 4) & 0xF).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo).astype(jnp.int8)
    hi = jnp.where(hi > 7, hi - 16, hi).astype(jnp.int8)
    out = jnp.stack([lo, hi], axis=2).reshape(packed.shape[0], -1)
    return out[:, :n]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """[K, N] float weight -> (quantized weight, per-N-channel scales).

    int8: [K, N] int8. int4: [K, ceil(N/2)] int8 bytes holding two
    4-bit values (reference packs the same way for its CUDA kernels)."""
    _check_algo(algo)
    if group_size not in (-1, None):
        raise NotImplementedError("grouped scales are not supported yet; "
                                  "use per-channel (group_size=-1)")

    def run(w):
        if algo == "weight_only_int8":
            return quantize_weight_int8(w, axis=1)
        bound = 7.0
        s = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-9)
        q = jnp.clip(jnp.round(w / s * bound), -bound, bound)
        return _pack_int4(q.astype(jnp.int8)), (s / bound).astype(jnp.float32)

    return apply_multi(run, x, name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float32"):
    """Inverse transform for inspection/tests."""
    _check_algo(algo)

    def run(q, s):
        if algo == "weight_only_int4":
            q = _unpack_int4(q, s.shape[0])
        return q.astype(out_dtype) * s.astype(out_dtype)

    return apply(run, x, scale, name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) [+ bias] (reference weight_only_linear).

    int8 runs the fused Pallas weight-only kernel on TPU; int4 unpacks to
    int8 in XLA (half HBM storage; the unpack fuses into the convert) and
    uses the same kernel."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8 or int4, "
                         f"got {weight_dtype!r}")
    if weight_scale is None:
        raise ValueError("weight_scale is required (from weight_quantize)")

    def run(xa, w, s, *maybe_bias):
        if weight_dtype == "int4":
            w = _unpack_int4(w, s.shape[0])
        y = dequant_matmul_int8(xa, w, s)
        if maybe_bias:
            y = y + maybe_bias[0].astype(y.dtype)
        return y

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(run, *args, name="weight_only_linear")
