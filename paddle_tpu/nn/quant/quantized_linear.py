"""Weight-only quantized linear (reference:
python/paddle/nn/quant/quantized_linear.py:25 `weight_quantize`, :70
`weight_dequantize`, :116 `weight_only_linear` — CUDA weight-only GEMM).

TPU mapping: int8 weights feed the fused Pallas weight-only matmul
(ops/kernels/wo_matmul_pallas.py — in-core dequant, halved HBM weight
traffic). int4 stores two nibbles per int8 byte in THIS FRAMEWORK'S
halves layout (byte j = columns j and j + N/2 — chosen so the dedicated
int4 Pallas kernel can sign-extend nibbles in VMEM without a lane
relayout; it is NOT the reference's CUDA interleaved packing, so packed
int4 blobs are not interchangeable across frameworks — requantize from
the float weights when migrating. The halves layout has been THE int4
format of this framework since int4 support shipped; no released artifact
ever used a different packing).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...autograd.function import apply, apply_multi
from ...quantization.functional import dequant_matmul_int8, \
    quantize_weight_int8

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear"]

_ALGOS = ("weight_only_int8", "weight_only_int4")


def _check_algo(algo):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r} "
                         f"(llm.int8 needs activation stats; use the "
                         f"quantization PTQ flow)")


def _pack_int4(q):
    """[K, N] int4 values in [-7, 7] -> [K, ceil(N/2)] bytes in the HALVES
    layout (byte j = columns j and j + N'/2): the layout the Pallas int4
    kernel consumes without a lane relayout (wo_matmul_pallas)."""
    from ...ops.kernels.wo_matmul_pallas import pack_int4_halves
    n = q.shape[1]
    if n % 2:
        q = jnp.pad(q, ((0, 0), (0, 1)))
    return pack_int4_halves(q)


def _unpack_int4(packed, n):
    """Inverse of _pack_int4 (drops the odd-N pad column)."""
    from ...ops.kernels.wo_matmul_pallas import unpack_int4_halves
    return unpack_int4_halves(packed)[:, :n]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """[K, N] float weight -> (quantized weight, scales).

    int8: [K, N] int8. int4: [K, ceil(N/2)] int8 bytes holding two 4-bit
    values in the halves layout (see module docstring; framework-specific
    — requantize rather than importing reference-packed int4 blobs).

    `group_size` in {64, 128, ...}: scales become per-(K-group, channel)
    [K/group_size, N] (the reference's grouped weight-only mode — finer
    scales recover accuracy on outlier-heavy weights); -1 = one scale per
    output channel."""
    _check_algo(algo)
    gs = -1 if group_size is None else int(group_size)
    if gs != -1 and gs < 1:
        raise ValueError(f"group_size must be -1 (per-channel) or a "
                         f"positive divisor of K, got {group_size}")

    def run(w):
        bound = 127.0 if algo == "weight_only_int8" else 7.0
        if gs == -1:
            if algo == "weight_only_int8":
                return quantize_weight_int8(w, axis=1)
            s = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-9)
            q = jnp.clip(jnp.round(w / s * bound), -bound, bound)
            return (_pack_int4(q.astype(jnp.int8)),
                    (s / bound).astype(jnp.float32))
        k, n = w.shape
        if k % gs:
            raise ValueError(f"group_size {gs} must divide K={k}")
        wg = w.reshape(k // gs, gs, n)
        s = jnp.maximum(jnp.max(jnp.abs(wg), axis=1), 1e-9)  # [K/gs, N]
        q = jnp.clip(jnp.round(wg / s[:, None] * bound), -bound, bound)
        q = q.reshape(k, n).astype(jnp.int8)
        scales = (s / bound).astype(jnp.float32)
        if algo == "weight_only_int4":
            return _pack_int4(q), scales
        return q, scales

    return apply_multi(run, x, name="weight_quantize")


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float32"):
    """Inverse transform for inspection/tests (per-channel [N] or grouped
    [K/gs, N] scales)."""
    _check_algo(algo)

    def run(q, s):
        if s.ndim == 2:
            from ...ops.kernels.wo_matmul_pallas import dequant_grouped
            n = s.shape[1]
            if algo == "weight_only_int4":
                q = _unpack_int4(q, n)
            return dequant_grouped(q, s).astype(out_dtype)
        if algo == "weight_only_int4":
            q = _unpack_int4(q, s.shape[0])
        return q.astype(out_dtype) * s.astype(out_dtype)

    return apply(run, x, scale, name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) [+ bias] (reference weight_only_linear).

    int8 and int4 each run a dedicated fused Pallas kernel on TPU; the
    int4 kernel reads the packed bytes straight from HBM and sign-extends
    nibbles in VMEM (half of int8's weight traffic)."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8 or int4, "
                         f"got {weight_dtype!r}")
    if weight_scale is None:
        raise ValueError("weight_scale is required (from weight_quantize)")

    def run(xa, w, s, *maybe_bias):
        if s.ndim == 2:
            # grouped scales: the int8 kernel rescales per K-group in VMEM;
            # int4 unpacks to int8 first (grouped-packed stays a composite)
            n = s.shape[1]
            if weight_dtype == "int4":
                w = _unpack_int4(w, n)
            y = dequant_matmul_int8(xa, w, s)
        elif weight_dtype == "int4":
            from ...quantization.functional import dequant_matmul_int4
            n, half = s.shape[0], w.shape[1]
            if 2 * half != n:   # odd N carries one zero pad column
                s = jnp.concatenate(
                    [s, jnp.zeros((2 * half - n,), s.dtype)])
            y = dequant_matmul_int4(xa, w, s)[..., :n]
        else:
            y = dequant_matmul_int8(xa, w, s)
        if maybe_bias:
            y = y + maybe_bias[0].astype(y.dtype)
        return y

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply(run, *args, name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8 mixed-precision linear (reference:
    python/paddle/nn/quant/quantized_linear.py:186 over the llm_int8
    CUDA kernels).

    TPU design: outlier activation channels (per-feature absmax >
    threshold) run in the original dtype; the dense remainder quantizes
    dynamically per row to int8 and contracts int8xint8 -> int32 on the
    MXU, then dequantizes by (row_scale x weight_scale). weight: int8
    [n, k] (row-major like the reference's out-feature-major layout);
    weight_scale: [n] float."""
    import jax.numpy as jnp

    from ...autograd.function import apply
    from ...core.flags import flag
    from ...core.tensor import as_tensor
    from ...ops.kernels import _common as kern
    from ...ops.kernels import a8w8_matmul_pallas as a8

    x_t, w_t = as_tensor(x), as_tensor(weight)
    args = [x_t, w_t]
    if weight_scale is not None:
        args.append(as_tensor(weight_scale))
    if bias is not None:
        args.append(as_tensor(bias))
    # the A8W8 Pallas kernel is inference-path (no custom_vjp): dispatch
    # whenever nothing can need a gradient through this linear — the same
    # need-grad test the autograd dispatcher uses (grad enabled AND some
    # input not stop_gradient), so no_grad serving with Parameter inputs
    # still takes the kernel
    from ...autograd.grad_mode import is_grad_enabled
    m_rows = 1
    for s in x_t.shape[:-1]:
        m_rows *= s
    needs_grad = (is_grad_enabled()
                  and any(not t.stop_gradient for t in args))
    pallas_ok = (kern.available() and flag("use_pallas_kernels")
                 and not needs_grad
                 and a8.use_kernel(m_rows, x_t.shape[-1]))

    def f(xa, wa, *rest):
        it = iter(rest)
        ws = next(it) if weight_scale is not None else \
            jnp.ones((wa.shape[0],), jnp.float32)
        ba = next(it) if bias is not None else None
        k = xa.shape[-1]
        x2 = xa.reshape(-1, k)
        # outlier decomposition: feature columns whose absmax crosses the
        # threshold stay in floating point (LLM.int8 core idea)
        col_max = jnp.max(jnp.abs(x2), axis=0)
        outlier = col_max > threshold                  # [k]
        x_dense = jnp.where(outlier[None, :], 0.0, x2)
        x_out = jnp.where(outlier[None, :], x2, 0.0)
        if pallas_ok:
            # prefill regime: per-token quant + int8 MXU contraction +
            # dequant in one VMEM pass, weight consumed in its [N, K]
            # storage layout (no HBM transpose)
            dense = a8.a8w8_matmul(x_dense, wa, ws, layout="nk",
                                   interpret=kern.interpret_mode()) \
                .astype(jnp.float32)
        else:
            # dynamic per-row int8 quantization of the dense part
            row_scale = jnp.maximum(jnp.max(jnp.abs(x_dense), axis=1),
                                    1e-9)
            q = jnp.clip(jnp.round(x_dense / row_scale[:, None] * 127.0),
                         -127, 127).astype(jnp.int8)
            acc = jnp.matmul(q.astype(jnp.int32), wa.T.astype(jnp.int32),
                             preferred_element_type=jnp.int32)
            dense = acc.astype(jnp.float32) * (row_scale[:, None] / 127.0) \
                * ws[None, :].astype(jnp.float32)
        # outlier columns contract in float against dequantized weights
        w_fp = wa.astype(jnp.float32) * ws[:, None].astype(jnp.float32)
        out = dense + x_out.astype(jnp.float32) @ w_fp.T
        out = out.astype(xa.dtype)
        if ba is not None:
            out = out + ba
        return out.reshape(xa.shape[:-1] + (wa.shape[0],))

    return apply(f, *args, name="llm_int8_linear")


__all__ += ["llm_int8_linear"]
