"""Pooling layer classes (reference: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from ..layer import Layer
from .. import functional as F

__all__ = ["MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    _fn = None
    _default_fmt = "NCHW"

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format=None,
                 return_mask=False, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format or self._default_fmt
        self.return_mask = return_mask


class AvgPool1D(_Pool):
    _default_fmt = "NCL"

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.exclusive, self.ceil_mode, self.data_format)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            divisor_override=self.divisor_override,
                            data_format=self.data_format)


class AvgPool3D(_Pool):
    _default_fmt = "NCDHW"

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            divisor_override=self.divisor_override,
                            data_format=self.data_format)


class MaxPool1D(_Pool):
    _default_fmt = "NCL"

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode, self.data_format)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode, self.data_format)


class MaxPool3D(_Pool):
    _default_fmt = "NCDHW"

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode, self.data_format)


class _AdaptivePool(Layer):
    def __init__(self, output_size, data_format=None, return_mask=False,
                 name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format
        self.return_mask = return_mask


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     self.data_format or "NCHW")


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     self.data_format or "NCDHW")


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    """Inverse max pool over the return_mask indices (reference
    nn/layer/pooling.py MaxUnPool1D)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.data_format = padding, data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool2D(MaxUnPool1D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool3D(MaxUnPool1D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)
