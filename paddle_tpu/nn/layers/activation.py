"""Activation layer classes (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from ..layer import Layer
from .. import functional as F

__all__ = ["Softmax2D", "ReLU", "ReLU6", "LeakyReLU", "ELU", "CELU", "SELU", "GELU",
           "Sigmoid", "LogSigmoid", "Hardsigmoid", "Hardswish", "Hardtanh",
           "Hardshrink", "Softshrink", "Tanhshrink", "Silu", "Swish", "Mish",
           "Softplus", "Softsign", "Tanh", "Softmax", "LogSoftmax", "Maxout",
           "ThresholdedReLU", "RReLU", "PReLU", "GLU"]


def _simple(name, fn_name, params=()):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        for i, p in enumerate(params):
            v = args[i] if i < len(args) else kwargs.get(p[0], p[1])
            setattr(self, p[0], v)

    def forward(self, x):
        fn = getattr(F, fn_name)
        return fn(x, *[getattr(self, p[0]) for p in params])

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Softsign = _simple("Softsign", "softsign")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Hardswish = _simple("Hardswish", "hardswish")
LeakyReLU = _simple("LeakyReLU", "leaky_relu", (("negative_slope", 0.01),))
ELU = _simple("ELU", "elu", (("alpha", 1.0),))
CELU = _simple("CELU", "celu", (("alpha", 1.0),))
SELU = _simple("SELU", "selu")
Hardshrink = _simple("Hardshrink", "hardshrink", (("threshold", 0.5),))
Softshrink = _simple("Softshrink", "softshrink", (("threshold", 0.5),))
Hardtanh = _simple("Hardtanh", "hardtanh", (("min", -1.0), ("max", 1.0)))
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Softplus = _simple("Softplus", "softplus", (("beta", 1.0), ("threshold", 20.0)))
Softmax = _simple("Softmax", "softmax", (("axis", -1),))
LogSoftmax = _simple("LogSoftmax", "log_softmax", (("axis", -1),))
Maxout = _simple("Maxout", "maxout", (("groups", 1), ("axis", 1)))
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu",
                          (("threshold", 1.0),))
GLU = _simple("GLU", "glu", (("axis", -1),))


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softmax2D(Layer):
    """Channel softmax for NCHW inputs (reference nn/layer/activation.py
    Softmax2D: softmax over C for each spatial position)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(f"Softmax2D expects 3-D/4-D input, got "
                             f"{x.ndim}-D")
        return F.softmax(x, axis=-3)
