"""Common layers (reference: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

import math

from ...core import dtype as dtypes
from ...core.tensor import Tensor
from ..layer import Layer
from .. import initializer as I
from .. import functional as F

__all__ = ["Fold", "PixelUnshuffle", "ChannelShuffle", "Unflatten",
           "Linear", "Identity", "Flatten", "Dropout", "Dropout2D", "Dropout3D",
           "AlphaDropout", "Embedding", "Upsample", "UpsamplingNearest2D",
           "UpsamplingBilinear2D", "Bilinear", "CosineSimilarity",
           "PairwiseDistance", "PixelShuffle", "Unfold", "Pad1D", "Pad2D", "Pad3D",
           "ZeroPad2D"]


class Linear(Layer):
    """y = xW + b with W:[in_features, out_features] (reference:
    python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        if self.bias is not None:
            self.add_parameter("bias", self.bias)

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, input):
        from ...ops.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, input):
        return F.dropout(input, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, input):
        return F.dropout2d(input, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, input):
        return F.dropout3d(input, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, self.p, training=self.training)


class Embedding(Layer):
    """Reference: python/paddle/nn/layer/common.py Embedding; weight
    [num_embeddings, embedding_dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = None if padding_idx is None else (
            padding_idx if padding_idx >= 0 else num_embeddings + padding_idx)
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if self._padding_idx is not None:
            import jax.numpy as jnp
            self.weight._data = self.weight._data.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter(shape=[1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ...ops.manipulation import einsum
        out = einsum("bi,oij,bj->bo", x1, self.weight, x2)
        return out + self.bias if self.bias is not None else out


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class _PadNd(Layer):
    _n_spatial = 1

    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        if isinstance(padding, int):
            # reference contract: a scalar pads every spatial boundary
            padding = [padding] * (2 * self._n_spatial)
        self.padding, self.mode = padding, mode
        self.value, self.data_format = value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    pass


class Pad2D(_PadNd):
    _n_spatial = 2

    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    _n_spatial = 3

    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Fold(Layer):
    """col2im layer (reference nn/layer/common.py Fold)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings = strides, paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unflatten(Layer):
    """Expand one axis into a shape (reference nn/layer/common.py
    Unflatten)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, list(shape)

    def forward(self, x):
        from ... import ops
        ax = self.axis % x.ndim
        new_shape = (list(x.shape[:ax]) + list(self.shape)
                     + list(x.shape[ax + 1:]))
        return ops.reshape(x, new_shape)

