"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py —
SimpleRNNCell :697, LSTMCell :876, GRUCell :1074, RNN :1269, BiRNN :1342,
SimpleRNN :1742, LSTM :1864, GRU :1990).

TPU-native redesign: the reference unrolls a Python loop over time steps
(`_rnn_dynamic_graph`, rnn.py:157) or dispatches to a cuDNN kernel. Here the
whole recurrence is ONE `lax.scan` inside one traced function — the cell is
functionalized (its params rebound to traced arrays, the same idiom as the
compiled pipeline) and scanned over the time axis, so XLA compiles a single
fused while-style loop whose per-step matmuls ride the MXU and whose
backward (BPTT) falls out of autodiff through the scan. Sequence-length
masking follows the reference's `_maybe_copy` contract exactly: step
OUTPUTS are not masked; STATES keep their previous value past each row's
length. Reverse runs flip the whole padded sequence (and the mask), as the
reference does.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd.function import apply_multi
from ...autograd.grad_mode import no_grad
from ..layer import Layer
from .. import initializer as I
from ..utils import bind_param_arrays

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _as_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


class RNNCellBase(Layer):
    """Base for single-step recurrent cells (reference rnn.py:551)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch_ref = _as_tensor(batch_ref)
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape if shape is not None else self.state_shape
        dt = dtype or "float32"

        def build(s):
            if isinstance(s, (list, tuple)) and s and \
                    isinstance(s[0], (list, tuple)):
                return tuple(build(sub) for sub in s)
            dims = [batch] + [int(d) for d in s]
            import numpy as np
            return Tensor(jnp.full(dims, init_value,
                                   jnp.dtype(np.dtype(dt))))

        return build(tuple(shape))


def _make_rnn_params(layer, n_gates, input_size, hidden_size,
                     weight_ih_attr, weight_hh_attr, bias_ih_attr,
                     bias_hh_attr):
    """Reference contract (rnn.py:777-840): attr=False does NOT omit the
    parameter — it creates a FROZEN one (Constant(1.0) weights, zero
    biases), keeping forward math and state_dict keys intact."""
    std = 1.0 / math.sqrt(hidden_size)

    def make(shape, attr, is_bias):
        if attr is not False:
            return layer.create_parameter(
                shape, attr, is_bias=is_bias,
                default_initializer=I.Uniform(-std, std))
        p = layer.create_parameter(
            shape, None, is_bias=is_bias,
            default_initializer=I.Constant(0.0 if is_bias else 1.0))
        p.stop_gradient = True
        return p

    layer.weight_ih = make((n_gates * hidden_size, input_size),
                           weight_ih_attr, False)
    layer.weight_hh = make((n_gates * hidden_size, hidden_size),
                           weight_hh_attr, False)
    layer.bias_ih = make((n_gates * hidden_size,), bias_ih_attr, True)
    layer.bias_hh = make((n_gates * hidden_size,), bias_hh_attr, True)


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference rnn.py:697)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        if activation not in ("tanh", "relu"):
            raise ValueError(f"Unknown activation '{activation}'")
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        _make_rnn_params(self, 1, input_size, hidden_size, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from .. import functional as F
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_h = states
        i2h = inputs.matmul(self.weight_ih, transpose_y=True) + self.bias_ih
        h2h = pre_h.matmul(self.weight_hh, transpose_y=True) + self.bias_hh
        act = F.tanh if self.activation == "tanh" else F.relu
        h = act(i2h + h2h)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class LSTMCell(RNNCellBase):
    """i,f,g,o gate LSTM step (reference rnn.py:876; gate order i,f,g,o)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        if proj_size is not None:
            raise NotImplementedError(
                "LSTM proj_size (hidden-state projection) is not "
                "implemented on this backend")
        self.input_size, self.hidden_size = input_size, hidden_size
        _make_rnn_params(self, 4, input_size, hidden_size, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from .. import functional as F
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_hidden, pre_cell = states
        gates = inputs.matmul(self.weight_ih, transpose_y=True) \
            + self.bias_ih \
            + pre_hidden.matmul(self.weight_hh, transpose_y=True) \
            + self.bias_hh
        from ...ops.manipulation import split
        gi, gf, gg, go = split(gates, 4, axis=-1)
        i = F.sigmoid(gi)
        f = F.sigmoid(gf)
        o = F.sigmoid(go)
        c = f * pre_cell + i * F.tanh(gg)
        h = o * F.tanh(c)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class GRUCell(RNNCellBase):
    """r,z,c gate GRU step, reset-after-matmul variant (reference
    rnn.py:1074: c = act(x_c + r * h_c); h = (h_prev - c) * z + c)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size, self.hidden_size = input_size, hidden_size
        _make_rnn_params(self, 3, input_size, hidden_size, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        from .. import functional as F
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_hidden = states
        x_gates = inputs.matmul(self.weight_ih, transpose_y=True) \
            + self.bias_ih
        h_gates = pre_hidden.matmul(self.weight_hh, transpose_y=True) \
            + self.bias_hh
        from ...ops.manipulation import split
        x_r, x_z, x_c = split(x_gates, 3, axis=-1)
        h_r, h_z, h_c = split(h_gates, 3, axis=-1)
        r = F.sigmoid(x_r + h_r)
        z = F.sigmoid(x_z + h_z)
        c = F.tanh(x_c + r * h_c)  # apply reset gate after matmul
        h = (pre_hidden - c) * z + c
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


def _scan_recurrence(cell, inputs, initial_states, sequence_length,
                     time_major, is_reverse, **cell_kwargs):
    """Run `cell` over the time axis as ONE compiled lax.scan.

    Replaces the reference's per-step Python loop (_rnn_dynamic_graph,
    rnn.py:157) with a single scan: the cell's params are rebound to traced
    arrays inside the traced function, so gradients flow to them through
    the scan (BPTT) via the framework's normal vjp machinery.
    Returns (outputs, final_states) with the reference's masking contract.
    """
    params = [p for _, p in cell.named_parameters()]
    x = _as_tensor(inputs)
    st_flat, st_def = jax.tree_util.tree_flatten(
        initial_states, is_leaf=lambda v: isinstance(v, Tensor))
    n_states = len(st_flat)
    has_seq = sequence_length is not None
    seq_in = [_as_tensor(sequence_length)] if has_seq else []

    def f(x_arr, *rest):
        rest = list(rest)
        seq_arr = rest.pop(0) if has_seq else None
        st0 = rest[:n_states]
        parr = rest[n_states:]
        xs = x_arr if time_major else jnp.swapaxes(x_arr, 0, 1)  # [T, B, I]
        t_steps = xs.shape[0]
        if has_seq:
            mask = (jnp.arange(t_steps)[:, None]
                    < seq_arr.reshape(1, -1)).astype(xs.dtype)   # [T, B]
            if is_reverse:
                mask = mask[::-1]
        if is_reverse:
            xs = xs[::-1]

        def step(carry, inp):
            st = carry
            x_t = inp[0] if has_seq else inp
            with bind_param_arrays(params, parr):
                with no_grad():
                    out, new_states = cell.forward(
                        Tensor(x_t),
                        jax.tree_util.tree_unflatten(
                            st_def, [Tensor(s) for s in st]),
                        **cell_kwargs)
            new_flat = [t._d for t in jax.tree_util.tree_leaves(
                new_states, is_leaf=lambda v: isinstance(v, Tensor))]
            if has_seq:
                m = inp[1][:, None]  # [B, 1]
                new_flat = [m * n + (1 - m) * o
                            for n, o in zip(new_flat, st)]
            return tuple(new_flat), out._d

        init = tuple(a.astype(xs.dtype) if a.dtype != xs.dtype else a
                     for a in st0)
        final, ys = jax.lax.scan(step, init,
                                 (xs, mask) if has_seq else xs)
        if is_reverse:
            ys = ys[::-1]
        out = ys if time_major else jnp.swapaxes(ys, 0, 1)
        return (out,) + tuple(final)

    outs = apply_multi(lambda *arrs: f(arrs[0], *arrs[1:]),
                       x, *seq_in, *st_flat, *params, name="rnn_scan")
    out, final_flat = outs[0], list(outs[1:])
    final_states = jax.tree_util.tree_unflatten(st_def, final_flat)
    return out, final_states


class RNN(Layer):
    """Wrap a cell to run over a whole sequence (reference rnn.py:1269)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        if not hasattr(self.cell, "call"):
            self.cell.call = self.cell.forward
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            initial_states = self.cell.get_initial_states(
                batch_ref=inputs,
                batch_dim_idx=1 if self.time_major else 0)
        return _scan_recurrence(self.cell, inputs, initial_states,
                                sequence_length, self.time_major,
                                self.is_reverse, **kwargs)


class BiRNN(Layer):
    """Forward + reverse cells, outputs concatenated (reference :1342)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        if states_fw is None:
            states_fw = self.cell_fw.get_initial_states(
                batch_ref=inputs, batch_dim_idx=1 if self.time_major else 0)
        if states_bw is None:
            states_bw = self.cell_bw.get_initial_states(
                batch_ref=inputs, batch_dim_idx=1 if self.time_major else 0)
        out_fw, st_fw = _scan_recurrence(
            self.cell_fw, inputs, states_fw, sequence_length,
            self.time_major, False, **kwargs)
        out_bw, st_bw = _scan_recurrence(
            self.cell_bw, inputs, states_bw, sequence_length,
            self.time_major, True, **kwargs)
        from ...ops.manipulation import concat
        outputs = concat([out_fw, out_bw], axis=-1)
        return outputs, (st_fw, st_bw)


class RNNBase(Layer):
    """Stacked (multi-layer, optionally bidirectional) recurrence
    (reference rnn.py:1426). States are packed [L*D, B, H] per component."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        if direction not in ("forward", "bidirectional", "bidirect"):
            raise ValueError(f"Unknown direction '{direction}'")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout = dropout
        self.num_directions = 2 if direction != "forward" else 1
        self.time_major = time_major
        self.num_layers = num_layers
        self.state_components = 2 if mode == "LSTM" else 1

        kwargs = {"weight_ih_attr": weight_ih_attr,
                  "weight_hh_attr": weight_hh_attr,
                  "bias_ih_attr": bias_ih_attr,
                  "bias_hh_attr": bias_hh_attr}
        if mode == "LSTM":
            cell_cls = LSTMCell
        elif mode == "GRU":
            cell_cls = GRUCell
        else:
            cell_cls = SimpleRNNCell
            kwargs["activation"] = self.activation

        self._layers_list = []
        for i in range(num_layers):
            in_size = input_size if i == 0 \
                else hidden_size * self.num_directions
            if self.num_directions == 2:
                layer = BiRNN(cell_cls(in_size, hidden_size, **kwargs),
                              cell_cls(in_size, hidden_size, **kwargs),
                              time_major)
            else:
                layer = RNN(cell_cls(in_size, hidden_size, **kwargs),
                            is_reverse=False, time_major=time_major)
            self.add_sublayer(str(i), layer)
            self._layers_list.append(layer)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F
        from ...ops.manipulation import stack, concat
        inputs = _as_tensor(inputs)
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        L, D, C = self.num_layers, self.num_directions, self.state_components
        if initial_states is None:
            z = Tensor(jnp.zeros((L * D, batch, self.hidden_size),
                                 inputs._data.dtype))
            initial_states = tuple(z for _ in range(C))
        elif isinstance(initial_states, Tensor):
            initial_states = (initial_states,)

        final_per_layer = []
        out = inputs
        for i, layer in enumerate(self._layers_list):
            if i > 0 and self.dropout:
                out = F.dropout(out, self.dropout, training=self.training,
                                mode="upscale_in_train")
            # states for this layer: component tensors rows [i*D, i*D+D)
            def pick(row):
                comps = tuple(s[row] for s in initial_states)
                return comps if C == 2 else comps[0]
            if D == 2:
                st = (pick(i * D), pick(i * D + 1))
            else:
                st = pick(i * D)
            out, fin = layer(out, st, sequence_length)
            final_per_layer.append(fin)

        # repack final states to [L*D, B, H] per component
        comps = []
        for ci in range(C):
            rows = []
            for i in range(L):
                fin = final_per_layer[i]
                if D == 2:
                    for d in range(2):
                        f_d = fin[d]
                        rows.append(f_d[ci] if C == 2 else f_d)
                else:
                    rows.append(fin[ci] if C == 2 else fin)
            comps.append(stack(rows, axis=0))
        final_states = tuple(comps) if C == 2 else comps[0]
        return out, final_states

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.num_layers != 1:
            s += f", num_layers={self.num_layers}"
        if self.time_major:
            s += f", time_major={self.time_major}"
        if self.dropout:
            s += f", dropout={self.dropout}"
        return s


class SimpleRNN(RNNBase):
    """Multi-layer Elman RNN (reference rnn.py:1742)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        if activation not in ("tanh", "relu"):
            raise ValueError(f"Unknown activation '{activation}'")
        self.activation = activation
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    """Multi-layer LSTM (reference rnn.py:1864)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=None,
                 name=None):
        if proj_size is not None:
            raise NotImplementedError(
                "LSTM proj_size (hidden-state projection) is not "
                "implemented on this backend")
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(RNNBase):
    """Multi-layer GRU (reference rnn.py:1990)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
