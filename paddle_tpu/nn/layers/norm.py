"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ..layer import Layer
from .. import initializer as I
from .. import functional as F

__all__ = ["SpectralNorm", "LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """RMS norm with learnable scale (reference: incubate fused_rms_norm;
    paddle/phi/kernels/fusion/gpu/fused_rms_norm*)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, input):
        return F.rms_norm(input, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        import jax.numpy as jnp
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None,
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None,
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. On TPU, batch statistics are synchronized
    automatically when the batch axis is sharded under pjit (psum of moments);
    eager single-process behavior equals BatchNorm (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight, self.bias = None, None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr,
                                              is_bias=True)
        self._data_format = data_format

    def forward(self, input):
        return F.instance_norm(input, weight=self.weight, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, input):
        import jax.numpy as jnp
        from ...autograd.function import apply
        sz, alpha, beta, k = self.size, self.alpha, self.beta, self.k
        ch_axis = 1 if self.data_format.startswith("NC") else -1

        def f(a):
            sq = jnp.square(a)
            c = a.shape[ch_axis]
            pads = [(0, 0)] * a.ndim
            pads[ch_axis % a.ndim] = (sz // 2, (sz - 1) // 2)
            sqp = jnp.pad(sq, pads)
            acc = sum(jnp.take(sqp, jnp.arange(i, i + c), axis=ch_axis)
                      for i in range(sz))
            return a / jnp.power(k + alpha * acc / sz, beta)
        return apply(f, input, name="local_response_norm")


class SpectralNorm(Layer):
    """Spectral normalization of a weight tensor by power iteration
    (reference: nn/layer/norm.py:1868 SpectralNorm over the spectral_norm
    op): forward(weight) returns weight / sigma_max, with persistent u/v
    estimate buffers updated functionally each call (jit-compatible)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32", name=None):
        super().__init__()
        import numpy as np

        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        import paddle_tpu as paddle
        self.weight_u = paddle.to_tensor(
            np.random.default_rng(0).standard_normal(h).astype("float32"))
        self.weight_v = paddle.to_tensor(
            np.random.default_rng(1).standard_normal(w).astype("float32"))
        self.register_buffer("weight_u", self.weight_u)
        self.register_buffer("weight_v", self.weight_v)

    def forward(self, weight):
        import jax.numpy as jnp

        from ...autograd.function import apply_multi

        dim, iters, eps = self._dim, self._power_iters, self._eps

        def f(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ (wm @ v)
            return w / sigma, u, v

        out, new_u, new_v = apply_multi(f, weight, self.weight_u,
                                        self.weight_v, name="spectral_norm")
        self.weight_u._data = new_u._data
        self.weight_v._data = new_v._data
        return out
