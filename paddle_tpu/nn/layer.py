"""`nn.Layer`: the module base class.

Reference: python/paddle/nn/layer/layers.py:337 (`Layer`). Parameters,
sublayers, and buffers are tracked via `__setattr__`; state_dict round-trips
through `paddle_tpu.save/load`; forward pre/post hooks match the reference's
hook API.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Iterator

import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor, Parameter
from ..framework.parameter import ParamAttr

__all__ = ["Layer"]


class _HookHandle:
    _next_id = 0

    def __init__(self, hooks: OrderedDict):
        self._hooks = hooks
        self._id = _HookHandle._next_id
        _HookHandle._next_id += 1
        hooks[self._id] = None  # placeholder replaced by caller

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: str | None = None, dtype: Any = "float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtypes.dtype_from_any(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks: OrderedDict = OrderedDict()
        self._forward_post_hooks: OrderedDict = OrderedDict()
        self._casted_dtype = None

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers is not None and layers.pop(name, None)
            buffers is not None and buffers.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params is not None and params.pop(name, None)
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params.pop(name)
                object.__setattr__(self, name, None)
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name!r}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        d = self.__dict__
        for store in ("_parameters", "_sub_layers", "_buffers"):
            s = d.get(store)
            if s is not None and name in s:
                return s[name]
        raise AttributeError(
            f"'{self.__class__.__name__}' object has no attribute {name!r}")

    def __delattr__(self, name: str):
        for store in (self._parameters, self._sub_layers, self._buffers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # -- registration -------------------------------------------------------
    def add_parameter(self, name: str, parameter: Parameter | None) -> Parameter | None:
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor | None,
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        from ..framework.parameter import create_parameter as _cp
        if attr is False:
            return None
        dt = dtype or self._dtype
        return _cp(shape, dtype=dt, attr=attr, is_bias=is_bias,
                   default_initializer=default_initializer)

    def create_tensor(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp
        return Tensor(jnp.zeros((), dtypes.dtype_from_any(dtype or self._dtype).np_dtype),
                      name=name)

    # -- iteration ----------------------------------------------------------
    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True) -> Iterator:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers: bool = True) -> list[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def buffers(self, include_sublayers: bool = True) -> list[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self: bool = False) -> list["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None or id(l) in layers_set:
                continue
            layers_set.add(id(l))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, l
            yield from l.named_sublayers(sub_prefix, False, layers_set)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- forward ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(
            f"{self.__class__.__name__} must implement forward()")

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            if hook is None:
                continue
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            if hook is None:
                continue
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def register_forward_pre_hook(self, hook) -> _HookHandle:
        h = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook) -> _HookHandle:
        h = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = owner._sub_layers[part]
            if short in owner._non_persistable_buffer_names:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                v_arr = v._data if isinstance(v, Tensor) else np.asarray(v)
                if tuple(tgt._data.shape) != tuple(np.shape(v_arr)):
                    raise ValueError(
                        f"shape mismatch for {k}: {tuple(tgt._data.shape)} vs "
                        f"{tuple(np.shape(v_arr))}")
                tgt.set_value(v_arr)
                matched.add(k)
            else:
                unexpected.append(k)
        missing = [k for k in own if k not in matched]
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(dtypes.dtype_from_any(dtype))
        return self

    def astype(self, dtype):
        self._cast_all(dtypes.dtype_from_any(dtype))
        return self

    def _cast_all(self, dt: dtypes.DType):
        for p in self.parameters():
            if dtypes.is_floating_point(p.dtype):
                p._data = p._data.astype(dt.np_dtype)
        for b in self.buffers():
            if b is not None and dtypes.is_floating_point(b.dtype):
                b._data = b._data.astype(dt.np_dtype)
        for l in self.sublayers(include_self=True):
            l._dtype = dt

    def float(self):
        return self.astype(dtypes.float32)

    def bfloat16(self):
        return self.astype(dtypes.bfloat16)

    def half(self):
        return self.astype(dtypes.float16)

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            mod_str = repr(l)
            mod_str = "\n".join("  " + ln for ln in mod_str.split("\n"))
            lines.append(f"  ({name}): {mod_str.strip()}")
        main = self.__class__.__name__
        if not lines:
            return f"{main}({extra})"
        return f"{main}({extra}\n" + "\n".join(lines) + "\n)"
