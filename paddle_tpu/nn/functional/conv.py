"""Convolution functionals over lax.conv_general_dilated (reference:
python/paddle/nn/functional/conv.py; kernels paddle/phi/kernels/*/conv*)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd.function import apply

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _tup(v, n):
    a = np.atleast_1d(v)
    if a.size == 1:
        a = np.repeat(a, n)
    return tuple(int(x) for x in a)


def _pad_arg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    a = np.atleast_1d(padding)
    if a.size == 1:
        return [(int(a[0]), int(a[0]))] * n
    if a.size == n:
        return [(int(p), int(p)) for p in a]
    if a.size == 2 * n:
        return [(int(a[2 * i]), int(a[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last,
          name):
    st, dl = _tup(stride, n), _tup(dilation, n)
    pad = _pad_arg(padding, n)
    if channel_last:
        # NHWC-style
        lhs_spec = "N" + "".join("DHW"[3 - n:]) + "C"
    else:
        lhs_spec = "NC" + "".join("DHW"[3 - n:])
    rhs_spec = "OI" + "".join("DHW"[3 - n:])
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        tuple([1] * (n + 2)), tuple([1] * (n + 2)), (lhs_spec, rhs_spec, out_spec))

    def f(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=st, padding=pad, rhs_dilation=dl,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
        if b:
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b[0].shape[0]
            out = out + b[0].reshape(shape)
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return apply(f, *args, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None) -> Tensor:
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format == "NLC", "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None) -> Tensor:
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format == "NHWC", "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None) -> Tensor:
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format == "NDHWC", "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last, name):
    st, dl = _tup(stride, n), _tup(dilation, n)
    opad = _tup(output_padding, n)
    if isinstance(padding, str):
        raise ValueError("string padding unsupported for transpose conv")
    pad = _pad_arg(padding, n)
    if channel_last:
        lhs_spec = "N" + "".join("DHW"[3 - n:]) + "C"
    else:
        lhs_spec = "NC" + "".join("DHW"[3 - n:])
    # paddle stores transpose-conv weight as [in, out/groups, *k]
    rhs_spec = "IO" + "".join("DHW"[3 - n:])
    dn = jax.lax.conv_dimension_numbers(
        tuple([1] * (n + 2)), tuple([1] * (n + 2)), (lhs_spec, rhs_spec, lhs_spec))

    def f(a, w, *b):
        k = w.shape[2:]
        # transposed conv = lhs-dilated conv with flipped effective padding
        tpad = [(dl[i] * (k[i] - 1) - pad[i][0],
                 dl[i] * (k[i] - 1) - pad[i][1] + opad[i]) for i in range(n)]
        wt = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        wt = jnp.swapaxes(wt, 0, 1)  # IO -> OI ordering after flip
        if groups > 1:
            # regroup for grouped transpose conv
            i_per, o_per = w.shape[0] // groups, w.shape[1]
            wt = w.reshape((groups, i_per) + w.shape[1:]) \
                .transpose((0, 2, 1) + tuple(range(3, 3 + n))) \
                .reshape((groups * o_per, i_per) + k)
            wt = jnp.flip(wt, axis=tuple(range(2, 2 + n)))
        out = jax.lax.conv_general_dilated(
            a, wt, window_strides=(1,) * n, padding=tpad, lhs_dilation=st,
            rhs_dilation=dl,
            dimension_numbers=jax.lax.conv_dimension_numbers(
                tuple([1] * (n + 2)), tuple([1] * (n + 2)),
                (lhs_spec, "OI" + "".join("DHW"[3 - n:]), lhs_spec)),
            feature_group_count=groups)
        if b:
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b[0].shape[0]
            out = out + b[0].reshape(shape)
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return apply(f, *args, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None) -> Tensor:
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC",
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None) -> Tensor:
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC",
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None) -> Tensor:
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC",
                           "conv3d_transpose")
