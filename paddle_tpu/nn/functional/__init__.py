"""`paddle.nn.functional` equivalent (reference: python/paddle/nn/functional/).

Re-exports activation primitives from the op library and adds the layer-level
functionals: linear/embedding/norms/conv/pool/dropout/losses/attention.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dtype as dtypes
from ...core import generator as gen_mod
from ...core.tensor import Tensor, as_tensor
from ...autograd.function import apply
from ...ops.activation import *  # noqa: F401,F403
from ...ops.activation import __all__ as _act_all
from ...ops.creation import one_hot  # noqa: F401
from ...ops.manipulation import pad  # noqa: F401
from .loss import *  # noqa: F401,F403
from .loss import __all__ as _loss_all
from .conv import *  # noqa: F401,F403
from .conv import __all__ as _conv_all
from .pooling import *  # noqa: F401,F403
from .pooling import __all__ as _pool_all
from .vision import *  # noqa: F401,F403
from .vision import __all__ as _vision_all

__all__ = list(_act_all) + list(_loss_all) + list(_conv_all) + list(_pool_all) + list(_vision_all) + [
    "linear", "embedding", "layer_norm", "rms_norm", "fused_rms_norm_add",
    "fused_dropout_add_norm", "batch_norm", "group_norm",
    "instance_norm", "normalize", "dropout", "dropout2d", "dropout3d",
    "alpha_dropout", "cosine_similarity", "pairwise_distance", "one_hot", "pad",
    "scaled_dot_product_attention", "sparse_attention", "interpolate",
    "upsample", "pixel_shuffle",
    "unfold", "label_smooth", "sequence_mask", "gumbel_softmax", "rope",
    "gather_tree", "elu_", "hardtanh_", "leaky_relu_", "softmax_",
    "thresholded_relu_",
]


def linear(x, weight, bias=None, name=None) -> Tensor:
    """y = x @ W (+ b); W stored [in_features, out_features] like the reference
    (paddle/phi/kernels/impl/matmul_kernel_impl.h dispatch via matmul)."""
    if bias is None:
        return apply(lambda a, w: a @ w, x, weight, name="linear")
    return apply(lambda a, w, b: a @ w + b, x, weight, bias, name="linear")


def embedding(x, weight, padding_idx=None, sparse=False, name=None) -> Tensor:
    idx = as_tensor(x)._data

    def f(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            out = jnp.where((idx == padding_idx)[..., None],
                            jnp.zeros((), out.dtype), out)
        return out
    return apply(f, weight, name="embedding")


def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5,
               name=None) -> Tensor:
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(normalized_shape) if normalized_shape is not None else 1

    def f(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        # compute statistics in float32 for bf16 stability (XLA fuses the cast)
        af = a.astype(jnp.float32) if a.dtype in (jnp.bfloat16, jnp.float16) else a
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        it = iter(wb)
        if weight is not None:
            out = out * next(it)
        if bias is not None:
            out = out + next(it)
        return out
    args = [x] + ([weight] if weight is not None else []) + \
        ([bias] if bias is not None else [])
    return apply(f, *args, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None) -> Tensor:
    """RMSNorm (reference: fused rms_norm kernel,
    paddle/phi/kernels/fusion/gpu/fused_rms_norm*). On TPU with a weight this
    dispatches to the fused Pallas forward+backward kernel."""
    from ...core.flags import flag
    from ...ops.kernels import _common as kern

    if weight is not None and kern.available() and flag("use_pallas_kernels"):
        from ...ops.kernels.rms_norm_pallas import rms_norm_fused
        return apply(
            lambda a, w: rms_norm_fused(a, w, None, epsilon,
                                        kern.interpret_mode())[0],
            x, weight, name="rms_norm")

    def f(a, *w):
        af = a.astype(jnp.float32) if a.dtype in (jnp.bfloat16, jnp.float16) else a
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        out = (af * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0]
        return out
    args = [x] + ([weight] if weight is not None else [])
    return apply(f, *args, name="rms_norm")


def fused_rms_norm_add(x, residual, weight, epsilon=1e-6, name=None):
    """(rmsnorm(x + residual) * weight, x + residual) — the pre-norm residual
    block primitive (reference: fused_rms_norm residual variants). One fused
    VMEM pass on TPU; XLA composite elsewhere."""
    from ...core.flags import flag
    from ...ops.kernels import _common as kern

    from ...autograd.function import apply_multi

    if kern.available() and flag("use_pallas_kernels"):
        from ...ops.kernels.rms_norm_pallas import rms_norm_fused
        return apply_multi(
            lambda a, r, w: rms_norm_fused(a, w, r, epsilon,
                                           kern.interpret_mode()),
            x, residual, weight, name="fused_rms_norm_add")

    def f(a, r, w):
        h = a + r
        hf = h.astype(jnp.float32) if h.dtype in (jnp.bfloat16, jnp.float16) else h
        ms = jnp.mean(jnp.square(hf), axis=-1, keepdims=True)
        return (hf * jax.lax.rsqrt(ms + epsilon)).astype(h.dtype) * w, h
    return apply_multi(f, x, residual, weight, name="fused_rms_norm_add")


def fused_dropout_add_norm(x, residual, weight, bias=None, p=0.0,
                           epsilon=1e-6, norm="rms", activation=None,
                           seed=None, training=True, name=None):
    """Transformer-block mega-kernel epilogue: ``activation(x)`` ->
    dropout -> ``+ residual`` -> rms/layer norm as ONE VMEM-resident
    Pallas pass on TPU (ops/kernels/block_fused_pallas.py, with a fused
    custom_vjp backward); identical-semantics XLA composite elsewhere.
    Returns ``(y, h)`` — the normalized output and the pre-norm residual
    sum (the next junction's residual stream).

    ``norm``: "rms" (no bias) | "layer". ``activation``: None (a
    projection output feeds the junction directly — the in-model case) |
    "gelu" (tanh form) | "swiglu" (x packed ``[.., 2I]``, residual
    ``[.., I]``). The dropout mask is a counter-hash of (seed, element
    index) — pass ``seed`` for a deterministic/per-step stream; without
    one a seed is drawn from the framework RNG at trace time (constant
    across steps inside ``to_static``, like ``fused_dropout_add``)."""
    from ...core.flags import flag
    from ...ops.kernels import _common as kern
    from ...ops.kernels import block_fused_pallas as bfp
    from ...autograd.function import apply_multi

    xt, rt = as_tensor(x), as_tensor(residual)
    p_eff = float(p) if training else 0.0
    if seed is None:
        if 0.0 < p_eff < 1.0:
            key = gen_mod.default_generator.split()
            seed = jax.random.randint(key, (), 0, 2147483647,
                                      dtype=jnp.int32)
        else:
            seed = 0
    seed_t = as_tensor(jnp.asarray(as_tensor(seed)._data, jnp.int32))

    use_kern = (kern.available() and flag("use_pallas_kernels")
                and bfp.use_kernel(tuple(xt.shape), tuple(rt.shape),
                                   activation))
    args = [xt, rt, weight] + ([bias] if bias is not None else [])
    has_bias = bias is not None

    if use_kern:
        def f(a, r, w, *rest):
            b = rest[0] if has_bias else None
            return bfp.fused_epilogue(a, r, w, b, seed_t._data, p_eff,
                                      epsilon, activation, norm, None,
                                      kern.interpret_mode())
    else:
        def f(a, r, w, *rest):
            b = rest[0] if has_bias else None
            return bfp.reference_fused_epilogue(a, r, w, b, seed_t._data,
                                                p_eff, epsilon, activation,
                                                norm)
    return apply_multi(f, *args, name="fused_dropout_add_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None) -> Tensor:
    ch_axis = 1 if data_format.startswith("NC") else -1
    rm, rv = as_tensor(running_mean), as_tensor(running_var)
    use_batch = training and not use_global_stats

    def f(a, *wb):
        axes = tuple(i for i in range(a.ndim) if i != ch_axis % a.ndim)
        if use_batch:
            mean = jnp.mean(a, axis=axes)
            var = jnp.var(a, axis=axes)
        else:
            mean, var = rm._data, rv._data
        shape = [1] * a.ndim
        shape[ch_axis % a.ndim] = a.shape[ch_axis % a.ndim]
        out = (a - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        it = iter(wb)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    if use_batch:
        # update running stats eagerly (matches reference kernel semantics)
        a = as_tensor(x)._data
        axes = tuple(i for i in range(a.ndim) if i != ch_axis % a.ndim)
        bm = jnp.mean(a, axis=axes)
        bv = jnp.var(a, axis=axes)
        rm._data = momentum * rm._data + (1 - momentum) * bm.astype(rm._data.dtype)
        rv._data = momentum * rv._data + (1 - momentum) * bv.astype(rv._data.dtype)

    args = [x] + ([weight] if weight is not None else []) + \
        ([bias] if bias is not None else [])
    return apply(f, *args, name="batch_norm")


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", name=None) -> Tensor:
    def f(a, *wb):
        if data_format.startswith("NC"):
            n, c = a.shape[0], a.shape[1]
            rest = a.shape[2:]
            g = a.reshape((n, num_groups, c // num_groups) + rest)
            axes = tuple(range(2, g.ndim))
            mean = jnp.mean(g, axis=axes, keepdims=True)
            var = jnp.var(g, axis=axes, keepdims=True)
            out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
            shape = [1] * a.ndim
            shape[1] = c
        else:
            n, c = a.shape[0], a.shape[-1]
            rest = a.shape[1:-1]
            g = a.reshape((n,) + rest + (num_groups, c // num_groups))
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
            mean = jnp.mean(g, axis=axes, keepdims=True)
            var = jnp.var(g, axis=axes, keepdims=True)
            out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
            shape = [1] * a.ndim
            shape[-1] = c
        it = iter(wb)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out
    args = [x] + ([weight] if weight is not None else []) + \
        ([bias] if bias is not None else [])
    return apply(f, *args, name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None) -> Tensor:
    def f(a, *wb):
        axes = tuple(range(2, a.ndim)) if data_format.startswith("NC") \
            else tuple(range(1, a.ndim - 1))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape = [1] * a.ndim
        shape[c_axis] = a.shape[c_axis]
        it = iter(wb)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out
    args = [x] + ([weight] if weight is not None else []) + \
        ([bias] if bias is not None else [])
    return apply(f, *args, name="instance_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None) -> Tensor:
    def f(a):
        n = jnp.linalg.norm(a, ord=p, axis=axis, keepdims=True)
        return a / jnp.maximum(n, epsilon)
    return apply(f, x, name="normalize")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None, rng_key=None) -> Tensor:
    """Dropout. Inside jitted code pass `rng_key` for per-step randomness;
    eagerly a fresh key is drawn from the global generator (reference RNG
    isolation semantics: fleet/layers/mpu/random.py)."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x, name="dropout_infer")
        return as_tensor(x) if not isinstance(x, Tensor) else x
    key = rng_key if rng_key is not None else gen_mod.default_generator.split()

    def f(a):
        shape = a.shape if axis is None else tuple(
            a.shape[i] if i in np.atleast_1d(axis) else 1 for i in range(a.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype))
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return apply(f, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None) -> Tensor:
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None) -> Tensor:
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None) -> Tensor:
    if not training or p == 0.0:
        return as_tensor(x) if not isinstance(x, Tensor) else x
    key = gen_mod.default_generator.split()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, jnp.asarray(alpha_p, a.dtype)) + coef_b
    return apply(f, x, name="alpha_dropout")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None) -> Tensor:
    def f(a, b):
        d = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return d / jnp.maximum(na * nb, eps)
    return apply(f, x1, x2, name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None) -> Tensor:
    return apply(lambda a, b: jnp.linalg.norm(a - b + epsilon, ord=p, axis=-1,
                                              keepdims=keepdim), x, y,
                 name="pairwise_distance")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None) -> Tensor:
    def f(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    args = [label] + ([prior_dist] if prior_dist is not None else [])
    return apply(f, *args, name="label_smooth")


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None) -> Tensor:
    l = as_tensor(lengths)._data
    m = int(maxlen) if maxlen is not None else int(jnp.max(l))
    mask = jnp.arange(m) < l[..., None]
    return Tensor(mask.astype(dtypes.dtype_from_any(dtype).np_dtype))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None) -> Tensor:
    key = gen_mod.default_generator.split()

    def f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            one = (jnp.arange(y.shape[axis]) ==
                   jnp.moveaxis(idx, axis, -1)).astype(y.dtype)
            y_hard = jnp.moveaxis(one, -1, axis)
            return y_hard + y - jax.lax.stop_gradient(y)
        return y
    return apply(f, x, name="gumbel_softmax")


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None) -> Tensor:
    """SDPA with [batch, seq, heads, head_dim] layout (reference:
    paddle/phi/kernels/gpu/flash_attn_kernel.cu API). Uses the Pallas flash
    kernel on TPU when enabled, else an XLA-fused reference path."""
    from ...core.flags import flag
    from ...ops.kernels import flash_attention as fa
    mask_arr = as_tensor(attn_mask)._data if attn_mask is not None else None

    if fa.available() and flag("use_pallas_kernels") and dropout_p == 0.0 \
            and mask_arr is None:
        return apply(lambda q, k, v: fa.flash_attention(q, k, v, causal=is_causal),
                     query, key, value, name="flash_attention")

    drop_key = gen_mod.default_generator.split() if dropout_p > 0.0 and training \
        else None

    def f(q, k, v):
        k, v = fa.expand_kv_heads(q, k, v)  # GQA composite fallback
        # [B, S, H, D] -> [B, H, S, D]
        qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        scale = 1.0 / math.sqrt(q.shape[-1])
        logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
        logits = logits.astype(jnp.float32)
        if is_causal:
            s, t = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((s, t), bool), t - s)
            logits = jnp.where(causal, logits, -jnp.inf)
        if mask_arr is not None:
            if jnp.issubdtype(mask_arr.dtype, jnp.bool_):
                logits = jnp.where(mask_arr, logits, -jnp.inf)
            else:
                logits = logits + mask_arr.astype(logits.dtype)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p),
                              jnp.zeros((), probs.dtype))
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
        return jnp.swapaxes(out, 1, 2)
    return apply(f, query, key, value, name="scaled_dot_product_attention")


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None) -> Tensor:
    """Block-sparse attention over a CSR pattern (reference:
    python/paddle/nn/functional/sparse_attention.py over
    sparse_attention kernels). query/key/value: [B, H, S, D]; offset
    [B, H, S+1], columns [B, H, nnz] give each query row's attended keys.

    TPU design: the ragged CSR is expanded host-side to flat (row, col) edge
    lists (the pattern is static data, exactly how the reference feeds its
    kernel), then the edge-wise scores are computed densely on the VPU and
    reduced with segment softmax — no S×S materialization.
    """
    import numpy as np

    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    off = np.asarray(as_tensor(sparse_csr_offset).numpy(), np.int64)
    cols = np.asarray(as_tensor(sparse_csr_columns).numpy(), np.int64)
    b, h, s, d = q.shape
    counts = off[..., 1:] - off[..., :-1]          # [B, H, S]
    # cols has one fixed nnz per (b,h); expand each CSR offset row to a flat
    # row-index list of that same length
    rows = np.stack([np.repeat(np.arange(s), counts[bi, hi])
                     for bi in range(b) for hi in range(h)]).reshape(b, h, -1)
    rows_j = jnp.asarray(rows)
    cols_j = jnp.asarray(cols)

    # key_padding_mask: [B, S] (0/False = padded key); attn_mask: additive
    # [B, H, S, S] or broadcastable — both gathered down to per-edge values
    kpm = (as_tensor(key_padding_mask)._data
           if key_padding_mask is not None else None)
    am = as_tensor(attn_mask)._data if attn_mask is not None else None

    def f(qa, ka, va):
        scale = 1.0 / math.sqrt(d)
        nnz = rows_j.shape[-1]
        bh_b = jnp.repeat(jnp.arange(b), h)  # batch id per (b*h) slice
        bh_h = jnp.tile(jnp.arange(h), b)

        def one(qbh, kbh, vbh, r, c, bi, hi):
            e = jnp.sum(jnp.take(qbh, r, axis=0) * jnp.take(kbh, c, axis=0),
                        -1) * scale                      # [nnz]
            e = e.astype(jnp.float32)
            if am is not None:
                amb = jnp.broadcast_to(am, (b, h, s, s)).astype(jnp.float32)
                e = e + amb[bi, hi][r, c]
            if kpm is not None:
                keep = jnp.broadcast_to(kpm, (b, s))[bi]
                if jnp.issubdtype(keep.dtype, jnp.bool_):
                    dead = ~jnp.take(keep, c)
                else:
                    dead = jnp.take(keep, c) == 0
                e = jnp.where(dead, -jnp.inf, e)
            m = jax.ops.segment_max(e, r, num_segments=s)
            m = jnp.where(jnp.isfinite(m), m, 0.0)  # fully-masked rows
            p = jnp.exp(e - jnp.take(m, r))
            z = jax.ops.segment_sum(p, r, num_segments=s)
            w = p / jnp.take(jnp.maximum(z, 1e-30), r)
            return jax.ops.segment_sum(
                w[:, None].astype(vbh.dtype) * jnp.take(vbh, c, axis=0), r,
                num_segments=s)

        flat = jax.vmap(one)(qa.reshape(b * h, s, d), ka.reshape(b * h, s, d),
                             va.reshape(b * h, s, d),
                             rows_j.reshape(b * h, -1),
                             cols_j.reshape(b * h, -1), bh_b, bh_h)
        return flat.reshape(b, h, s, d)

    return apply(f, q, k, v, name="sparse_attention")


def rope(q, k, sin, cos, name=None):
    """Rotary position embedding applied to q and k
    (reference: fused_rope kernel, paddle/phi/kernels/fusion/gpu/fused_rope*).

    On TPU this dispatches to the fused Pallas kernel (one VMEM pass per
    tensor; the adjoint reuses the same kernel with -sin), falling back to
    the XLA composite elsewhere."""
    from ...core.flags import flag
    from ...ops.kernels import _common as kern
    sin_a, cos_a = as_tensor(sin)._data, as_tensor(cos)._data

    qt, kt = as_tensor(q), as_tensor(k)

    def _kernel_ok(t):
        return (t.ndim == 4 and t.shape[-1] % 2 == 0
                and cos_a.size == t.shape[1] * t.shape[-1])

    # both tensors ride the same kernel path, so BOTH layouts must fit it
    # (a 3-D or different-seq-len k the composite accepts must not crash
    # inside rope_apply's [b, s, h, d] unpack)
    use_kernel = (kern.available() and flag("use_pallas_kernels")
                  and _kernel_ok(qt) and _kernel_ok(kt))
    if use_kernel:
        from ...ops.kernels import rope_pallas as rp

        def fq(a):
            return rp.rope_apply(a, cos_a, sin_a, kern.interpret_mode())
    else:
        def rot(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jnp.concatenate([-a2, a1], axis=-1)

        def fq(a):
            return a * cos_a.astype(a.dtype) + rot(a) * sin_a.astype(a.dtype)
    q_out = apply(fq, q, name="rope_q")
    k_out = apply(fq, k, name="rope_k")
    return q_out, k_out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None) -> Tensor:
    x_t = as_tensor(x) if not isinstance(x, Tensor) else x
    nd = x_t.ndim
    spatial = nd - 2
    if data_format.startswith("NC"):
        sp_axes = list(range(2, nd))
    else:
        sp_axes = list(range(1, nd - 1))
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        tgt = [int(s) for s in np.atleast_1d(size)]
    else:
        sf = np.atleast_1d(scale_factor).astype(float)
        if sf.size == 1:
            sf = np.repeat(sf, spatial)
        tgt = [int(x_t.shape[a] * s) for a, s in zip(sp_axes, sf)]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        shape = list(a.shape)
        for ax, t in zip(sp_axes, tgt):
            shape[ax] = t
        return jax.image.resize(a, shape, method=jmode)
    return apply(f, x_t, name="interpolate")


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None) -> Tensor:
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return apply(f, x, name="pixel_shuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None) -> Tensor:
    ks = np.broadcast_to(np.atleast_1d(kernel_sizes), (2,))
    st = np.broadcast_to(np.atleast_1d(strides), (2,))
    pd = np.broadcast_to(np.atleast_1d(paddings), (2,))
    dl = np.broadcast_to(np.atleast_1d(dilations), (2,))

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = a[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [N, C, k*k, OH, OW]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply(f, x, name="unfold")


def gather_tree(ids, parents, name=None) -> Tensor:
    """Backtrace beam-search ancestry to full sequences (reference:
    nn/functional/extension.py:135, gather_tree CUDA kernel). ids/parents:
    [max_time, batch, beam]. Implemented as one reverse lax.scan — the
    TPU-native form of the reference's per-timestep backtrack loop."""
    def f(ids_a, par_a):
        ids_i = ids_a.astype(jnp.int64)
        par_i = par_a.astype(jnp.int64)
        t, b, k = ids_i.shape
        b_rows = jnp.arange(b)[:, None]

        def back(beams, xs):
            # beams: [B, K] beam index selecting step t's entries for each
            # final beam; out[t] = ids[t][beams], next = parents[t][beams]
            ids_t, par_t = xs
            out_t = ids_t[b_rows, beams]
            prev = par_t[b_rows, beams]
            return prev, out_t

        init = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int64)[None], (b, k))
        _, outs = jax.lax.scan(back, init, (ids_i, par_i), reverse=True)
        return outs

    return apply(f, ids, parents, name="gather_tree")


# flash attention module surface (reference functional/__init__.py:83
# imports from .flash_attention; flash_attention/flash_attn_unpadded are
# used via the module path paddle.nn.functional.flash_attention.*)
from . import flash_attention  # noqa: F401,E402


# -- in-place activation variants (reference *_ surface; rebind contract) ---

def elu_(x, alpha=1.0, name=None) -> Tensor:
    from ...ops.math import _rebind
    return _rebind(x, elu(x, alpha))


def hardtanh_(x, min=-1.0, max=1.0, name=None) -> Tensor:
    from ...ops.math import _rebind
    return _rebind(x, hardtanh(x, min, max))


def leaky_relu_(x, negative_slope=0.01, name=None) -> Tensor:
    from ...ops.math import _rebind
    return _rebind(x, leaky_relu(x, negative_slope))


def softmax_(x, axis=-1, dtype=None, name=None) -> Tensor:
    from ...ops.math import _rebind
    return _rebind(x, softmax(x, axis=axis, dtype=dtype))


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None) -> Tensor:
    from ...ops.math import _rebind
    return _rebind(x, thresholded_relu(x, threshold, value))
