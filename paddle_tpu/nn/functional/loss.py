"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, as_tensor
from ...autograd.function import apply

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "log_loss", "square_error_cost", "triplet_margin_loss",
    "sigmoid_focal_loss", "dice_loss", "ctc_loss", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss", "npair_loss",
    "multi_margin_loss", "gaussian_nll_loss",
    "triplet_margin_with_distance_loss", "margin_cross_entropy",
    "hsigmoid_loss", "rnnt_loss", "edit_distance",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None) -> Tensor:
    lbl = as_tensor(label)._data
    w_arr = as_tensor(weight)._data if weight is not None else None

    def f(logits):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) \
            if use_softmax else jnp.log(jnp.clip(logits.astype(jnp.float32),
                                                 1e-12, None))
        n_class = logits.shape[axis]
        if soft_label:
            tgt = lbl.astype(logp.dtype)
            if label_smoothing > 0.0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_class
            if w_arr is not None:
                shape = [1] * logp.ndim
                shape[axis] = n_class
                tgt = tgt * w_arr.astype(logp.dtype).reshape(shape)
            loss = -jnp.sum(tgt * logp, axis=axis)
            return _reduce(loss, reduction)
        idx = lbl
        if idx.ndim == logp.ndim and idx.shape[axis] == 1:
            idx = jnp.squeeze(idx, axis)
        idx = idx.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
        if label_smoothing > 0.0:
            smooth = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * smooth
        loss = -picked
        if w_arr is not None:
            cw = jnp.take(w_arr.astype(logp.dtype), safe)
            loss = loss * cw
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(
                    valid, cw, 0.0)), 1e-12)
        else:
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    return apply(f, input, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from ...ops.activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(x, y, *w):
        xs = jnp.clip(x.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
        out = -(y * jnp.log(xs) + (1 - y) * jnp.log1p(-xs))
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [input, as_tensor(label)] + ([weight] if weight is not None else [])
    return apply(f, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    pw = as_tensor(pos_weight)._data if pos_weight is not None else None

    def f(x, y, *w):
        x = x.astype(jnp.float32)
        y = y.astype(jnp.float32)
        log_sig = jax.nn.log_sigmoid(x)
        log_1msig = jax.nn.log_sigmoid(-x)
        if pw is not None:
            out = -(pw * y * log_sig + (1 - y) * log_1msig)
        else:
            out = -(y * log_sig + (1 - y) * log_1msig)
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [logit, as_tensor(label)] + ([weight] if weight is not None else [])
    return apply(f, *args, name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda x, y: _reduce(jnp.square(x - y), reduction),
                 input, as_tensor(label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda x, y: _reduce(jnp.abs(x - y), reduction),
                 input, as_tensor(label), name="l1_loss")


def square_error_cost(input, label, name=None):
    return apply(lambda x, y: jnp.square(x - y), input, as_tensor(label),
                 name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(x, y):
        return -(y * jnp.log(x + epsilon) + (1 - y) * jnp.log(1 - x + epsilon))
    return apply(f, input, as_tensor(label), name="log_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lbl = as_tensor(label)._data.astype(jnp.int32)
    w_arr = as_tensor(weight)._data if weight is not None else None

    def f(logp):
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -picked
        cw = jnp.take(w_arr.astype(logp.dtype), safe) if w_arr is not None \
            else valid.astype(logp.dtype)
        loss = jnp.where(valid, loss * cw, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(cw * valid), 1e-12)
        return _reduce(loss, reduction)
    return apply(f, input, name="nll_loss")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(x, y):
        if log_target:
            out = jnp.exp(y) * (y - x)
        else:
            out = y * (jnp.log(jnp.clip(y, 1e-12, None)) - x)
        if reduction == "batchmean":
            return jnp.sum(out) / x.shape[0]
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="kl_div")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(x, y):
        d = x - y
        ad = jnp.abs(d)
        out = jnp.where(ad < delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="smooth_l1_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        out = jnp.maximum(-y * (a - b) + margin, 0.0)
        return _reduce(out, reduction)
    return apply(f, input, other, as_tensor(label), name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        out = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(out, reduction)
    return apply(f, input1, input2, as_tensor(label), name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(x, y):
        out = jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(f, input, positive, negative, name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    norm = as_tensor(normalizer)._data if normalizer is not None else None

    def f(x, y):
        x = x.astype(jnp.float32)
        p = jax.nn.sigmoid(x)
        ce = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * jnp.power(1 - p_t, gamma) * ce
        if norm is not None:
            out = out / norm
        return _reduce(out, reduction)
    return apply(f, logit, as_tensor(label), name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    lbl = as_tensor(label)._data

    def f(x):
        n_class = x.shape[-1]
        oh = (lbl.squeeze(-1)[..., None] == jnp.arange(n_class)).astype(x.dtype)
        inter = jnp.sum(x * oh, axis=tuple(range(1, x.ndim)))
        union = jnp.sum(x, axis=tuple(range(1, x.ndim))) + \
            jnp.sum(oh, axis=tuple(range(1, x.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply(f, input, name="dice_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="poisson_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def f(x, y, *w):
        out = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        out = jnp.mean(out, axis=-1)
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [input, as_tensor(label)] + ([weight] if weight is not None else [])
    return apply(f, *args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply(f, input, as_tensor(label), name="soft_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the classic alpha-recursion in log space (lax.scan over time).
    Reference: warpctc-backed paddle ctc_loss."""
    lbl = as_tensor(labels)._data.astype(jnp.int32)
    in_len = as_tensor(input_lengths)._data.astype(jnp.int32)
    lb_len = as_tensor(label_lengths)._data.astype(jnp.int32)

    def f(lp):
        # lp: [T, B, C] logits (paddle layout) -> log-probs
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended label seq: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl)
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(lp[0, jnp.arange(B), ext[:, 1]])

        same = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_prev2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a_prev2 = jnp.where(same, neg_inf, a_prev2)
            m = jnp.maximum(jnp.maximum(alpha, a_prev1), a_prev2)
            new = m + jnp.log(
                jnp.exp(alpha - m) + jnp.exp(a_prev1 - m) + jnp.exp(a_prev2 - m))
            new = jnp.where(m <= neg_inf / 2, neg_inf, new)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return new + emit, new + emit

        alphaT, hist = jax.lax.scan(step, alpha0, lp[1:])
        hist = jnp.concatenate([alpha0[None], hist], axis=0)  # [T, B, S]
        # pick alpha at t = input_length-1, s = 2*label_length or 2*label_length-1
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        a_final = hist[t_idx, jnp.arange(B)]  # [B, S]
        s1 = jnp.clip(2 * lb_len, 0, S - 1)
        s2 = jnp.clip(2 * lb_len - 1, 0, S - 1)
        la = a_final[jnp.arange(B), s1]
        lb_ = a_final[jnp.arange(B), s2]
        m = jnp.maximum(la, lb_)
        ll = m + jnp.log(jnp.exp(la - m) + jnp.exp(lb_ - m))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lb_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return apply(f, log_probs, name="ctc_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair metric loss (reference loss.py:313): l2 regularizer on the
    embeddings + soft-label CE over the anchor/positive similarity matrix."""
    lab = as_tensor(labels)

    def f(a, p, y):
        b = y.shape[0]
        y2 = jnp.tile(y.reshape(b, 1), (1, b))
        soft = (y2 == y2.T).astype(jnp.float32)
        soft = soft / jnp.sum(soft, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(jnp.square(a), 1))
              + jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25 * l2_reg
        sim = jnp.matmul(a, p.T)
        ce_rows = -jnp.sum(
            soft * jax.nn.log_softmax(sim.astype(jnp.float32), -1), -1)
        # soft's rows are normalized, so the reference's soft-weighted
        # column-sum + mean collapses to the plain row mean
        return l2 + jnp.mean(ce_rows)

    return apply(f, anchor, positive, lab, name="npair_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class hinge loss (reference loss.py:3863)."""
    lab = as_tensor(label)

    def f(x, y, *w):
        # exact reference formula (loss.py:3960): the j==label term is
        # included in the mean then subtracted as margin^p/C (scaled by
        # weight[label] when weighted, matching the reference's quirk for
        # p>1)
        n, c = x.shape
        tgt = jnp.take_along_axis(x, y.reshape(n, 1).astype(jnp.int32), 1)
        diff = jnp.maximum(margin - tgt + x, 0.0)
        if w:
            wl = jnp.take(w[0], y.astype(jnp.int32)).reshape(n, 1)
            per = jnp.mean((wl * diff) ** p, axis=1, keepdims=True) \
                - wl * (margin ** p / c)
        else:
            per = jnp.mean(diff ** p, axis=1, keepdims=True) \
                - margin ** p / c
        per = per.reshape(n)
        return _reduce(per, reduction)

    args = [input, lab] + ([weight] if weight is not None else [])
    return apply(f, *args, name="multi_margin_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Gaussian NLL (reference loss.py:4086): 0.5*(log(max(var,eps)) +
    (input-label)^2 / max(var,eps)) [+ 0.5*log(2*pi) when full]."""
    import math as _math

    def f(x, y, v):
        v = jnp.maximum(v, epsilon)
        out = 0.5 * (jnp.log(v) + jnp.square(x - y) / v)
        if full:
            out = out + 0.5 * _math.log(2 * _math.pi)
        return _reduce(out, reduction)

    return apply(f, input, as_tensor(label), as_tensor(variance),
                 name="gaussian_nll_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Triplet loss with a custom distance callable (reference
    loss.py:3583); default distance is pairwise L2."""
    def f(a, p, n):
        if distance_function is not None:
            dp = distance_function(Tensor(a), Tensor(p))._data
            dn = distance_function(Tensor(a), Tensor(n))._data
            if swap:
                dpn = distance_function(Tensor(p), Tensor(n))._data
                dn = jnp.minimum(dn, dpn)
        else:
            def l2(u, w):
                return jnp.sqrt(jnp.maximum(
                    jnp.sum(jnp.square(u - w), -1), 1e-12))
            dp, dn = l2(a, p), l2(a, n)
            if swap:
                dn = jnp.minimum(dn, l2(p, n))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply(f, input, positive, negative,
                 name="triplet_margin_with_distance_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (reference loss.py:2080): the target
    logit cos(theta) becomes cos(m1*theta + m2) - m3 before scaling. The
    class dim may be sharded under mp; GSPMD partitions the softmax the
    way the reference's model-parallel kernel does by hand."""
    lab = as_tensor(label)

    def f(x, y):
        n, c = x.shape
        y1 = y.reshape(n).astype(jnp.int32)
        cos_t = jnp.clip(jnp.take_along_axis(
            x, y1.reshape(n, 1), 1).reshape(n), -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(y1, c, dtype=x.dtype)
        mod = (x * (1.0 - oh) + target.reshape(n, 1) * oh) * scale
        logp = jax.nn.log_softmax(mod.astype(jnp.float32), -1)
        loss = -jnp.take_along_axis(logp, y1.reshape(n, 1), 1).reshape(n, 1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    if return_softmax:
        from ...autograd.function import apply_multi
        out, sm = apply_multi(f, logits, lab, name="margin_cross_entropy")
        return out, sm
    return apply(f, logits, lab, name="margin_cross_entropy")


import functools as _functools


@_functools.lru_cache(maxsize=32)
def _hsigmoid_default_tree(num_classes):
    """(path_table, path_code, path_mask) for the complete binary tree —
    O(C·depth) python construction, cached per num_classes (hierarchical
    softmax exists for large C; rebuilding per forward would dominate)."""
    import numpy as _np
    depth = max(int(_np.ceil(_np.log2(max(num_classes, 2)))), 1)
    table = _np.zeros((num_classes, depth), _np.int32)
    code = _np.zeros((num_classes, depth), _np.float32)
    mask = _np.zeros((num_classes, depth), _np.float32)
    for c in range(num_classes):
        node = c + num_classes
        path = []
        while node > 1:
            path.append((node // 2 - 1, float(node % 2)))
            node //= 2
        for d, (row, bit) in enumerate(reversed(path)):
            table[c, d] = row
            code[c, d] = bit
            mask[c, d] = 1.0
    return jnp.asarray(table), jnp.asarray(code), jnp.asarray(mask)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference loss.py:885). Default tree: the
    complete binary tree over `num_classes` leaves (heap numbering; leaf of
    class c is node c + num_classes, internal node k>=1 owns weight row
    k-1). Custom trees pass path_table/path_code like the reference."""
    lab = as_tensor(label)

    if path_table is None:
        path_table_a, path_code_a, path_mask = _hsigmoid_default_tree(
            num_classes)
    else:
        path_table_a = as_tensor(path_table)._data.astype(jnp.int32)
        path_code_a = as_tensor(path_code)._data.astype(jnp.float32)
        # reference CustomCode contract: negative entries pad shorter paths
        path_mask = (path_table_a >= 0).astype(jnp.float32)
        path_table_a = jnp.maximum(path_table_a, 0)

    def f(x, y, w, *b):
        y1 = y.reshape(-1).astype(jnp.int32)
        rows = jnp.take(path_table_a, y1, axis=0)      # [N, D]
        bits = jnp.take(path_code_a, y1, axis=0)       # [N, D]
        msk = jnp.take(path_mask, y1, axis=0)
        wv = jnp.take(w, rows, axis=0)                 # [N, D, F]
        logit = jnp.einsum("ndf,nf->nd", wv.astype(jnp.float32),
                           x.astype(jnp.float32))
        if b:
            logit = logit + jnp.take(b[0].reshape(-1), rows)
        # BCE-with-logits against the path code bits, masked to path length
        per = jnp.maximum(logit, 0) - logit * bits + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))
        return jnp.sum(per * msk, axis=1, keepdims=True)

    args = [input, lab, weight] + ([bias] if bias is not None else [])
    return apply(f, *args, name="hsigmoid_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (reference loss.py:1953, warprnnt-backed):
    log-space alpha recursion over the [T, U+1] lattice via lax.scan;
    autodiff through the DP yields the exact gradient.

    FastEmit (arXiv:2010.11148) matches warprnnt's implementation: the
    loss VALUE is the plain transducer loss, but gradients flowing through
    label-emission transitions are scaled by (1 + lambda). Because we get
    gradients by autodiff through the DP, the scaling is expressed as a
    forward-identity / backward-scale on the emission log-probs."""
    lbl = as_tensor(label)._data.astype(jnp.int32)
    in_len = as_tensor(input_lengths)._data.astype(jnp.int32)
    lb_len = as_tensor(label_lengths)._data.astype(jnp.int32)

    def f(logits):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        bsz, t_max, u_max, _ = lp.shape          # u_max = U + 1
        blank_lp = lp[..., blank]                # [B, T, U+1]
        u_idx = jnp.arange(u_max - 1)
        y_lp = jnp.take_along_axis(
            lp[:, :, :-1, :], lbl[:, None, :, None].repeat(t_max, 1),
            axis=-1)[..., 0]                     # [B, T, U]
        if fastemit_lambda:
            # forward value unchanged; d/dy_lp scaled by (1 + lambda) —
            # exactly warprnnt's FastEmit emission-gradient reweighting
            lam = jnp.float32(fastemit_lambda)
            y_lp = (1.0 + lam) * y_lp - lam * jax.lax.stop_gradient(y_lp)
        neg_inf = jnp.asarray(-1e30, jnp.float32)

        def lse(a, b):
            m = jnp.maximum(a, b)
            out = m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))
            return jnp.where(m <= neg_inf / 2, neg_inf, out)

        def u_scan(alpha_row_t, t):
            # alpha_row_t: [B, U+1] = alpha[t-1, :]; produce alpha[t, :]
            from_blank = alpha_row_t + blank_lp[:, t - 1, :]

            def emit_step(carry, u):
                # carry: alpha[t, u-1]; alpha[t,u] = lse(from_blank[u],
                #                         alpha[t, u-1] + y_lp[t, u-1])
                cur = lse(from_blank[:, u], carry + y_lp[:, t, u - 1])
                return cur, cur

            first = from_blank[:, 0]
            _, rest = jax.lax.scan(emit_step, first,
                                   jnp.arange(1, u_max))
            return jnp.concatenate([first[:, None], rest.T], axis=1)

        # alpha[0, u]: only label emissions along t=0
        def first_row(carry, u):
            cur = carry + y_lp[:, 0, u - 1]
            return cur, cur

        a00 = jnp.zeros((bsz,), jnp.float32)
        _, row0_rest = jax.lax.scan(first_row, a00, jnp.arange(1, u_max))
        alpha0 = jnp.concatenate([a00[:, None], row0_rest.T], axis=1)

        def t_step(alpha_prev, t):
            alpha_t = u_scan(alpha_prev, t)
            return alpha_t, alpha_t

        _, hist = jax.lax.scan(t_step, alpha0, jnp.arange(1, t_max))
        hist = jnp.concatenate([alpha0[None], hist], axis=0)  # [T, B, U+1]
        t_fin = jnp.clip(in_len - 1, 0, t_max - 1)
        u_fin = jnp.clip(lb_len, 0, u_max - 1)
        b_idx = jnp.arange(bsz)
        a_fin = hist[t_fin, b_idx, u_fin]
        ll = a_fin + blank_lp[b_idx, t_fin, u_fin]
        loss = -ll
        return _reduce(loss, reduction)

    return apply(f, input, name="rnnt_loss")


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance per batch row (reference loss.py:457): returns
    (distance [B, 1], sequence_num). Not differentiable (metric op)."""
    a = as_tensor(input)._data.astype(jnp.int32)
    b = as_tensor(label)._data.astype(jnp.int32)
    bsz, ta = a.shape
    tb = b.shape[1]
    a_len = as_tensor(input_length)._data.astype(jnp.int32) \
        if input_length is not None else jnp.full((bsz,), ta, jnp.int32)
    b_len = as_tensor(label_length)._data.astype(jnp.int32) \
        if label_length is not None else jnp.full((bsz,), tb, jnp.int32)
    if ignored_tokens:
        # drop ignored tokens by compacting each row (stable partition)
        def compact(seq, ln):
            keep = jnp.ones(seq.shape, bool)
            for tok in ignored_tokens:
                keep &= seq != tok
            idx = jnp.argsort(~keep, stable=True)
            return jnp.take(seq, idx), jnp.sum(
                keep & (jnp.arange(seq.shape[0]) < ln))
        a, a_len = jax.vmap(compact)(a, a_len)
        b, b_len = jax.vmap(compact)(b, b_len)

    def one(av, bv, la, lb_):
        prev = jnp.minimum(jnp.arange(tb + 1), lb_).astype(jnp.float32)

        def row(prev_row, i):
            in_a = i < la

            def cell(carry, j):
                sub = prev_row[j] + jnp.where(av[i] == bv[j], 0.0, 1.0)
                cur = jnp.minimum(jnp.minimum(prev_row[j + 1] + 1.0,
                                              carry + 1.0), sub)
                cur = jnp.where(j < lb_, cur, carry)  # freeze past label end
                return cur, cur

            first = jnp.float32(i + 1)
            _, rest = jax.lax.scan(cell, first, jnp.arange(tb))
            new_row = jnp.concatenate([first[None], rest])
            return jnp.where(in_a, new_row, prev_row), None

        final, _ = jax.lax.scan(row, prev, jnp.arange(ta))
        return final[jnp.clip(lb_, 0, tb)]

    dist = jax.vmap(one)(a, b, a_len, b_len)
    if normalized:
        dist = dist / jnp.maximum(b_len.astype(jnp.float32), 1.0)
    return (Tensor(dist.reshape(bsz, 1)),
            Tensor(jnp.asarray(bsz, jnp.int64)))
