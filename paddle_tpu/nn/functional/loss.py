"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, as_tensor
from ...autograd.function import apply

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "cosine_embedding_loss",
    "hinge_embedding_loss", "log_loss", "square_error_cost", "triplet_margin_loss",
    "sigmoid_focal_loss", "dice_loss", "ctc_loss", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None) -> Tensor:
    lbl = as_tensor(label)._data
    w_arr = as_tensor(weight)._data if weight is not None else None

    def f(logits):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) \
            if use_softmax else jnp.log(jnp.clip(logits.astype(jnp.float32),
                                                 1e-12, None))
        n_class = logits.shape[axis]
        if soft_label:
            tgt = lbl.astype(logp.dtype)
            if label_smoothing > 0.0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / n_class
            if w_arr is not None:
                shape = [1] * logp.ndim
                shape[axis] = n_class
                tgt = tgt * w_arr.astype(logp.dtype).reshape(shape)
            loss = -jnp.sum(tgt * logp, axis=axis)
            return _reduce(loss, reduction)
        idx = lbl
        if idx.ndim == logp.ndim and idx.shape[axis] == 1:
            idx = jnp.squeeze(idx, axis)
        idx = idx.astype(jnp.int32)
        valid = idx != ignore_index
        safe = jnp.where(valid, idx, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
        if label_smoothing > 0.0:
            smooth = jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * smooth
        loss = -picked
        if w_arr is not None:
            cw = jnp.take(w_arr.astype(logp.dtype), safe)
            loss = loss * cw
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(
                    valid, cw, 0.0)), 1e-12)
        else:
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    return apply(f, input, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from ...ops.activation import softmax as _softmax
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(x, y, *w):
        xs = jnp.clip(x.astype(jnp.float32), 1e-12, 1.0 - 1e-7)
        out = -(y * jnp.log(xs) + (1 - y) * jnp.log1p(-xs))
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [input, as_tensor(label)] + ([weight] if weight is not None else [])
    return apply(f, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    pw = as_tensor(pos_weight)._data if pos_weight is not None else None

    def f(x, y, *w):
        x = x.astype(jnp.float32)
        y = y.astype(jnp.float32)
        log_sig = jax.nn.log_sigmoid(x)
        log_1msig = jax.nn.log_sigmoid(-x)
        if pw is not None:
            out = -(pw * y * log_sig + (1 - y) * log_1msig)
        else:
            out = -(y * log_sig + (1 - y) * log_1msig)
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [logit, as_tensor(label)] + ([weight] if weight is not None else [])
    return apply(f, *args, name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda x, y: _reduce(jnp.square(x - y), reduction),
                 input, as_tensor(label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda x, y: _reduce(jnp.abs(x - y), reduction),
                 input, as_tensor(label), name="l1_loss")


def square_error_cost(input, label, name=None):
    return apply(lambda x, y: jnp.square(x - y), input, as_tensor(label),
                 name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(x, y):
        return -(y * jnp.log(x + epsilon) + (1 - y) * jnp.log(1 - x + epsilon))
    return apply(f, input, as_tensor(label), name="log_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    lbl = as_tensor(label)._data.astype(jnp.int32)
    w_arr = as_tensor(weight)._data if weight is not None else None

    def f(logp):
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -picked
        cw = jnp.take(w_arr.astype(logp.dtype), safe) if w_arr is not None \
            else valid.astype(logp.dtype)
        loss = jnp.where(valid, loss * cw, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(cw * valid), 1e-12)
        return _reduce(loss, reduction)
    return apply(f, input, name="nll_loss")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(x, y):
        if log_target:
            out = jnp.exp(y) * (y - x)
        else:
            out = y * (jnp.log(jnp.clip(y, 1e-12, None)) - x)
        if reduction == "batchmean":
            return jnp.sum(out) / x.shape[0]
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="kl_div")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(x, y):
        d = x - y
        ad = jnp.abs(d)
        out = jnp.where(ad < delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="smooth_l1_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        out = jnp.maximum(-y * (a - b) + margin, 0.0)
        return _reduce(out, reduction)
    return apply(f, input, other, as_tensor(label), name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        out = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(out, reduction)
    return apply(f, input1, input2, as_tensor(label), name="cosine_embedding_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(x, y):
        out = jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(f, input, positive, negative, name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    norm = as_tensor(normalizer)._data if normalizer is not None else None

    def f(x, y):
        x = x.astype(jnp.float32)
        p = jax.nn.sigmoid(x)
        ce = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * jnp.power(1 - p_t, gamma) * ce
        if norm is not None:
            out = out / norm
        return _reduce(out, reduction)
    return apply(f, logit, as_tensor(label), name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    lbl = as_tensor(label)._data

    def f(x):
        n_class = x.shape[-1]
        oh = (lbl.squeeze(-1)[..., None] == jnp.arange(n_class)).astype(x.dtype)
        inter = jnp.sum(x * oh, axis=tuple(range(1, x.ndim)))
        union = jnp.sum(x, axis=tuple(range(1, x.ndim))) + \
            jnp.sum(oh, axis=tuple(range(1, x.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply(f, input, name="dice_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(x, y):
        if log_input:
            out = jnp.exp(x) - y * x
        else:
            out = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return _reduce(out, reduction)
    return apply(f, input, as_tensor(label), name="poisson_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def f(x, y, *w):
        out = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        out = jnp.mean(out, axis=-1)
        if w:
            out = out * w[0]
        return _reduce(out, reduction)
    args = [input, as_tensor(label)] + ([weight] if weight is not None else [])
    return apply(f, *args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply(f, input, as_tensor(label), name="soft_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the classic alpha-recursion in log space (lax.scan over time).
    Reference: warpctc-backed paddle ctc_loss."""
    lbl = as_tensor(labels)._data.astype(jnp.int32)
    in_len = as_tensor(input_lengths)._data.astype(jnp.int32)
    lb_len = as_tensor(label_lengths)._data.astype(jnp.int32)

    def f(lp):
        # lp: [T, B, C] logits (paddle layout) -> log-probs
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended label seq: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl)
        neg_inf = jnp.asarray(-1e30, jnp.float32)
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(lp[0, jnp.arange(B), ext[:, 1]])

        same = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            a_prev2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            a_prev2 = jnp.where(same, neg_inf, a_prev2)
            m = jnp.maximum(jnp.maximum(alpha, a_prev1), a_prev2)
            new = m + jnp.log(
                jnp.exp(alpha - m) + jnp.exp(a_prev1 - m) + jnp.exp(a_prev2 - m))
            new = jnp.where(m <= neg_inf / 2, neg_inf, new)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return new + emit, new + emit

        alphaT, hist = jax.lax.scan(step, alpha0, lp[1:])
        hist = jnp.concatenate([alpha0[None], hist], axis=0)  # [T, B, S]
        # pick alpha at t = input_length-1, s = 2*label_length or 2*label_length-1
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        a_final = hist[t_idx, jnp.arange(B)]  # [B, S]
        s1 = jnp.clip(2 * lb_len, 0, S - 1)
        s2 = jnp.clip(2 * lb_len - 1, 0, S - 1)
        la = a_final[jnp.arange(B), s1]
        lb_ = a_final[jnp.arange(B), s2]
        m = jnp.maximum(la, lb_)
        ll = m + jnp.log(jnp.exp(la - m) + jnp.exp(lb_ - m))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lb_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return apply(f, log_probs, name="ctc_loss")
