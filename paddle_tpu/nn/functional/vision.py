"""Vision functionals (reference: python/paddle/nn/functional/vision.py —
affine_grid, grid_sample, pixel_unshuffle, channel_shuffle, temporal_shift
— plus common.py fold/bilinear/zeropad2d, norm.py local_response_norm and
the partial-FC class_center_sample from common.py).

All are pure jnp compositions: gathers/interpolation fuse under XLA, and
the scatter-adds (fold) lower to efficient TPU scatter ops.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, as_tensor
from ...autograd.function import apply, apply_multi

__all__ = [
    "affine_grid", "grid_sample", "pixel_unshuffle", "channel_shuffle",
    "temporal_shift", "local_response_norm", "zeropad2d", "bilinear",
    "fold", "class_center_sample",
]


def affine_grid(theta, out_shape, align_corners=True, name=None) -> Tensor:
    """Sampling grid from batched affine matrices (reference
    vision.py affine_grid): theta [N, 2, 3] + out [N, C, H, W] ->
    grid [N, H, W, 2]; theta [N, 3, 4] -> [N, D, H, W, 3]."""
    tt = as_tensor(theta)
    nd = 3 if tt.shape[-2] == 3 else 2
    sp = tuple(int(s) for s in out_shape)[2:]
    if len(sp) != nd:
        raise ValueError(f"theta is {nd}-D ({tt.shape[-2]}x{tt.shape[-1]}) "
                         f"but out_shape has {len(sp)} spatial dims")

    def f(th):
        def axis_coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

        axes = [axis_coords(s) for s in sp]            # slowest..fastest
        mesh = jnp.meshgrid(*axes, indexing="ij")      # each [*sp]
        # base grid columns ordered (x, y[, z]) = fastest-varying first
        cols = list(reversed(mesh)) + [jnp.ones(sp)]
        base = jnp.stack(cols, axis=-1)                # [*sp, nd+1]
        out = jnp.einsum("...k,njk->n...j", base, th)  # [N, *sp, nd]
        return out.astype(th.dtype)

    return apply(f, tt, name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None) -> Tensor:
    """Sample x [N, C, H, W] at normalized grid [N, Ho, Wo, 2] (x, y in
    [-1, 1]; reference vision.py grid_sample). Modes: bilinear | nearest;
    padding: zeros | border | reflection."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")

    def unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) * (size - 1) / 2.0
        return ((coord + 1.0) * size - 1.0) / 2.0

    def reflect(idx, size):
        if size == 1:
            return jnp.zeros_like(idx)
        # reflect across the valid range borders (align_corners handling
        # matches the reference: reflect about -0.5/size-0.5 when False)
        lo, hi = (0.0, size - 1.0) if align_corners else (-0.5, size - 0.5)
        span = hi - lo
        idx = (idx - lo) % (2 * span)
        idx = jnp.where(idx > span, 2 * span - idx, idx) + lo
        return idx

    def f(a, g):
        n, c, h, w = a.shape
        gx = unnormalize(g[..., 0].astype(jnp.float32), w)
        gy = unnormalize(g[..., 1].astype(jnp.float32), h)
        if padding_mode == "reflection":
            gx = reflect(gx, w)
            gy = reflect(gy, h)

        def gather(iy, ix):
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            vals = a[jnp.arange(n)[:, None, None], :, iyc, ixc]
            vals = jnp.moveaxis(vals, -1, 1)           # [N, C, Ho, Wo]
            if padding_mode == "zeros":
                inb = ((iy >= 0) & (iy <= h - 1) & (ix >= 0)
                       & (ix <= w - 1))
                vals = vals * inb[:, None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            return gather(jnp.round(gy).astype(jnp.int32),
                          jnp.round(gx).astype(jnp.int32)).astype(a.dtype)
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = (gx - x0)[:, None]
        wy = (gy - y0)[:, None]
        x0i = x0.astype(jnp.int32)
        y0i = y0.astype(jnp.int32)
        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        top = v00 * (1 - wx) + v01 * wx
        bot = v10 * (1 - wx) + v11 * wx
        return (top * (1 - wy) + bot * wy).astype(a.dtype)

    return apply(f, x, grid, name="grid_sample")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW",
                    name=None) -> Tensor:
    """Inverse of pixel_shuffle (reference vision.py pixel_unshuffle)."""
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply(f, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None) -> Tensor:
    """Interleave channel groups (reference vision.py channel_shuffle,
    the ShuffleNet mixing op)."""

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            a = a.transpose(0, 2, 1, 3, 4)
            return a.reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        a = a.transpose(0, 1, 2, 4, 3)
        return a.reshape(n, h, w, c)

    return apply(f, x, name="channel_shuffle")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None) -> Tensor:
    """TSM channel shift across the time axis (reference:
    nn/functional/extension.py temporal_shift): x [N*T, C, H, W]; the
    first fold of channels shifts t-1 -> t, the second t+1 -> t."""

    def f(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        nt, c, h, w = a.shape
        t = seg_num
        n = nt // t
        fold = int(c * shift_ratio)
        v = a.reshape(n, t, c, h, w)
        past = jnp.pad(v[:, :-1, :fold], ((0, 0), (1, 0), (0, 0), (0, 0),
                                          (0, 0)))
        future = jnp.pad(v[:, 1:, fold:2 * fold],
                         ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        out = jnp.concatenate([past, future, v[:, :, 2 * fold:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(f, x, name="temporal_shift")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None) -> Tensor:
    """AlexNet-style LRN across channels (reference norm.py
    local_response_norm): x / (k + alpha/size * sum window(x^2))^beta."""

    def f(a):
        cl = data_format in ("NLC", "NHWC", "NDHWC")
        ax = a.ndim - 1 if cl else 1
        sq = jnp.square(a)
        lo = (size - 1) // 2
        hi = size - 1 - lo
        pads = [(0, 0)] * a.ndim
        pads[ax] = (lo, hi)
        sqp = jnp.pad(sq, pads)
        win = jax.lax.reduce_window(
            sqp, jnp.zeros((), a.dtype), jax.lax.add,
            tuple(size if i == ax else 1 for i in range(a.ndim)),
            (1,) * a.ndim, "VALID")
        return a / jnp.power(k + alpha / size * win, beta)

    return apply(f, x, name="local_response_norm")


def zeropad2d(x, padding, data_format="NCHW", name=None) -> Tensor:
    """Zero-pad H/W (reference common.py zeropad2d; padding
    [left, right, top, bottom])."""
    pl_, pr, pt, pb = (int(p) for p in padding)

    def f(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl_, pr)))
        return jnp.pad(a, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))

    return apply(f, x, name="zeropad2d")


def bilinear(x1, x2, weight, bias=None, name=None) -> Tensor:
    """Bilinear transform out[b, o] = x1[b] W[o] x2[b]^T (+ bias)
    (reference common.py bilinear over the bilinear_tensor_product op)."""
    args = [as_tensor(x1), as_tensor(x2), as_tensor(weight)]
    if bias is not None:
        args.append(as_tensor(bias))

    def f(a, b, w, *mb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out + mb[0] if mb else out

    return apply(f, *args, name="bilinear")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None) -> Tensor:
    """col2im: inverse of unfold (reference common.py fold) — patches
    [N, C*kh*kw, L] scatter-add back to [N, C, H, W]."""
    os_ = np.broadcast_to(np.atleast_1d(output_sizes), (2,))
    ks = np.broadcast_to(np.atleast_1d(kernel_sizes), (2,))
    st = np.broadcast_to(np.atleast_1d(strides), (2,))
    pd = np.broadcast_to(np.atleast_1d(paddings), (2,))
    dl = np.broadcast_to(np.atleast_1d(dilations), (2,))

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        hp = os_[0] + 2 * pd[0]
        wp = os_[1] + 2 * pd[1]
        oh = (hp - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (wp - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = a.reshape(n, c, ks[0] * ks[1], oh, ow)
        out = jnp.zeros((n, c, hp, wp), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = (slice(None), slice(None),
                      slice(i * dl[0], i * dl[0] + oh * st[0], st[0]),
                      slice(j * dl[1], j * dl[1] + ow * st[1], st[1]))
                out = out.at[sl].add(v[:, :, i * ks[1] + j])
        return out[:, :, pd[0]:hp - pd[0], pd[1]:wp - pd[1]]

    return apply(f, x, name="fold")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Partial-FC negative-class sampling (reference common.py
    class_center_sample): keep every positive class plus uniformly sampled
    negatives up to num_samples; returns (remapped_label,
    sampled_class_indices). Deterministic per framework seed."""
    from ...core import generator as gen_mod

    lt = as_tensor(label)
    key = gen_mod.default_generator.split()

    def f(lab):
        pos = jnp.zeros((num_classes,), jnp.bool_).at[lab].set(True)
        # rank positives first (stable), then shuffled negatives
        r = jax.random.uniform(key, (num_classes,))
        order = jnp.argsort(jnp.where(pos, -1.0, r))
        sampled = jnp.sort(order[:num_samples])
        # remap: position of each label inside `sampled`
        inv = jnp.zeros((num_classes,), jnp.int32).at[sampled].set(
            jnp.arange(num_samples, dtype=jnp.int32))
        return inv[lab], sampled.astype(jnp.int32)

    return apply_multi(f, lt, name="class_center_sample")
