"""paddle.nn.functional.flash_attention surface.

Reference: python/paddle/nn/functional/flash_attention.py:142
(`flash_attention`), :301 (`flash_attn_unpadded` — packed varlen batches
addressed by cumulative sequence offsets), both dispatching to the FA2 CUDA
kernels (paddle/phi/kernels/gpu/flash_attn_kernel.cu). Here both map onto
the Pallas TPU flash kernels; the varlen path converts `cu_seqlens` into
per-token segment ids and uses the kernels' segment masking (packed
sequences attend only within their own segment).

Deviation from the reference, made loud: attention-probability dropout is
NOT supported — the TPU kernels never materialize the probability matrix,
so `dropout > 0` with `training=True` raises instead of silently changing
semantics (the reference drops individual attention links in-kernel).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...autograd.function import apply
from ...ops.kernels import flash_attention as fa

__all__ = ["flash_attention", "flash_attn_unpadded"]


def _reject_unsupported(dropout, training, return_softmax):
    if return_softmax:
        raise ValueError("return_softmax is not supported by the TPU flash "
                         "attention kernel (the probability matrix is never "
                         "materialized)")
    if dropout and training:
        raise NotImplementedError(
            "attention-probability dropout is not supported by the TPU "
            "flash attention kernel (it never materializes the matrix the "
            "reference kernel drops from); train with dropout=0.0, or apply "
            "nn.functional.dropout to the attention OUTPUT explicitly if "
            "that regularization is acceptable")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, *, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """[B, S, H, D] -> (out, None)."""
    _reject_unsupported(dropout, training, return_softmax)
    out = apply(lambda q, k, v: fa.flash_attention(q, k, v, causal=causal),
                query, key, value, name="flash_attention")
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Packed varlen attention: `query/key/value` are [total_tokens, H, D]
    with `cu_seqlens_q/k` [n_seqs+1] cumulative offsets (reference
    flash_attn_unpadded). Sequences attend only within themselves; `causal`
    applies inside each sequence.

    TPU mapping: offsets -> per-token segment ids (searchsorted), then ONE
    kernel launch over the packed [1, total, H, D] layout with segment
    masking — no unpack/pad round-trip. The stream is zero-padded up to the
    kernel's block multiple under a dedicated padding segment (sliced away
    after), so any total length stays on the kernel path instead of
    falling back to the O(S^2) composite.
    """
    _reject_unsupported(dropout, training, return_softmax)
    cu_q_host = np.asarray(
        cu_seqlens_q.numpy() if hasattr(cu_seqlens_q, "numpy")
        else cu_seqlens_q)
    cu_k_host = np.asarray(
        cu_seqlens_k.numpy() if hasattr(cu_seqlens_k, "numpy")
        else cu_seqlens_k)
    if cu_q_host.shape != cu_k_host.shape or \
            not np.array_equal(cu_q_host, cu_k_host):
        raise NotImplementedError(
            "flash_attn_unpadded on TPU supports self-attention packing "
            f"(cu_seqlens_q == cu_seqlens_k); got q offsets "
            f"{cu_q_host.tolist()} vs k offsets {cu_k_host.tolist()} — "
            "differing q/k splits would need two-sided segment masking")

    def run(q, k, v, cu_q):
        total = q.shape[0]
        if k.shape[0] != total:
            raise ValueError(
                f"flash_attn_unpadded packs q and kv to the same token "
                f"stream; got {total} vs {k.shape[0]} tokens")
        if scale is not None:
            # the kernel applies 1/sqrt(d); fold any custom scale into q
            q = q * jnp.asarray(scale * (q.shape[-1] ** 0.5), q.dtype)
        seg = jnp.searchsorted(jnp.asarray(cu_q)[1:-1], jnp.arange(total),
                               side="right").astype(jnp.int32)
        # kernel constraint: seq % min(256, seq) == 0 — any length <= 256
        # passes as-is; longer streams pad to the 256 block multiple
        pad = (-total) % 256 if total > 256 else 0
        if pad:
            n_seq = int(cu_q_host.shape[0]) - 1
            seg = jnp.concatenate(
                [seg, jnp.full((pad,), n_seq + 1, jnp.int32)])
            zeros = jnp.zeros((pad,) + q.shape[1:], q.dtype)
            q = jnp.concatenate([q, zeros])
            k = jnp.concatenate([k, zeros])
            v = jnp.concatenate([v, zeros])
        out = fa.flash_attention(q[None], k[None], v[None], causal=causal,
                                 segment_ids=seg[None])
        return out[0, :total]

    out = apply(run, query, key, value, cu_seqlens_q,
                name="flash_attn_unpadded")
    return out, None
