"""Pooling functionals over lax.reduce_window (reference:
python/paddle/nn/functional/pooling.py)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd.function import apply

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
           "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
           "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d"]


def _max_init(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.asarray(-jnp.inf, dt)
    return jnp.asarray(jnp.iinfo(dt).min, dt)


def _tup(v, n):
    a = np.atleast_1d(v)
    if a.size == 1:
        a = np.repeat(a, n)
    return tuple(int(x) for x in a)


def _ceil_extra(size, k, st, pd):
    """Extra high-side padding so the output has ceil((size+2p-k)/st)+1
    windows (reference ceil_mode contract; windows are clipped to the
    padded extent)."""
    rem = (size + 2 * pd - k) % st
    return (st - rem) if rem else 0


def _pool(x, kernel, stride, padding, n, channel_last, reducer, init, name,
          ceil_mode=False, count_include_pad=True, average=False,
          divisor=None):
    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)
    sp = (x.shape[-1 - n:-1] if channel_last else x.shape[-n:])
    ex = tuple(_ceil_extra(int(sp[i]), k[i], st[i], pd[i]) if ceil_mode
               else 0 for i in range(n))
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + st + (1,)
        pads = ((0, 0),) + tuple((p, p + e) for p, e in zip(pd, ex)) \
            + ((0, 0),)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple((p, p + e) for p, e in zip(pd, ex))

    def f(a):
        out = jax.lax.reduce_window(a, init(a.dtype), reducer, dims, strides, pads)
        if average:
            if divisor is not None:
                out = out / divisor
            elif count_include_pad:
                if any(e > 0 for e in ex):
                    # ceil-mode windows are clipped to the padded extent, so
                    # the include-pad divisor is the clipped window size
                    # min(start+k, size+2p) - start, not prod(k)
                    denom = jnp.ones((), out.dtype)
                    for i in range(n):
                        ext = int(sp[i]) + 2 * pd[i]
                        o_i = (ext + ex[i] - k[i]) // st[i] + 1
                        starts = jnp.arange(o_i) * st[i]
                        cnt_i = (jnp.minimum(starts + k[i], ext)
                                 - starts).astype(out.dtype)
                        shape = [1] * out.ndim
                        shape[(1 if channel_last else 2) + i] = o_i
                        denom = denom * cnt_i.reshape(shape)
                    out = out / denom
                else:
                    out = out / float(np.prod(k))
            else:
                ones = jnp.ones_like(a)
                cnt = jax.lax.reduce_window(ones, jnp.zeros((), a.dtype),
                                            jax.lax.add, dims, strides, pads)
                # a ceil-mode window can fall entirely in the pad margin:
                # the reference kernel emits 0 there, never 0/0
                out = jnp.where(cnt > 0, out / jnp.maximum(cnt, 1),
                                jnp.zeros((), out.dtype))
        return out
    return apply(f, x, name=name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None) -> Tensor:
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   data_format == "NLC", "max_pool1d",
                                   ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 jax.lax.max, _max_init,
                 "max_pool1d", ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None) -> Tensor:
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   data_format == "NHWC", "max_pool2d",
                                   ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.max, _max_init,
                 "max_pool2d", ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None) -> Tensor:
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   data_format == "NDHWC", "max_pool3d",
                                   ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.max, _max_init,
                 "max_pool3d", ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool1d",
                 ceil_mode=ceil_mode, count_include_pad=not exclusive,
                 average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool2d",
                 ceil_mode=ceil_mode, count_include_pad=not exclusive,
                 average=True, divisor=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool3d",
                 ceil_mode=ceil_mode, count_include_pad=not exclusive,
                 average=True, divisor=divisor_override)


def _adaptive(x, output_size, n, channel_last, mode, name):
    out_sz = _tup(output_size, n)

    def f(a):
        sp_axes = list(range(2, 2 + n)) if not channel_last else \
            list(range(1, 1 + n))
        out = a
        for i, ax in enumerate(sp_axes):
            in_sz = out.shape[ax]
            o = out_sz[i]
            if in_sz % o == 0:
                k = in_sz // o
                shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(shape)
                out = jnp.max(r, axis=ax + 1) if mode == "max" else \
                    jnp.mean(r, axis=ax + 1)
            else:
                # general adaptive: gather variable windows
                starts = (np.arange(o) * in_sz) // o
                ends = ((np.arange(o) + 1) * in_sz + o - 1) // o
                slices = []
                for s_, e_ in zip(starts, ends):
                    w = jnp.take(out, jnp.arange(s_, e_), axis=ax)
                    red = jnp.max(w, axis=ax, keepdims=True) if mode == "max" \
                        else jnp.mean(w, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out
    return apply(f, x, name=name)


def adaptive_avg_pool1d(x, output_size, name=None) -> Tensor:
    return _adaptive(x, output_size, 1, False, "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None) -> Tensor:
    return _adaptive(x, output_size, 2, data_format == "NHWC", "avg",
                     "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None) -> Tensor:
    return _adaptive(x, output_size, 3, data_format == "NDHWC", "avg",
                     "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 1, False, "max", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 2, False, "max", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 3, False, "max", "adaptive_max_pool3d")


def _max_pool_with_mask(x, kernel, stride, padding, n, channel_last, name,
                        ceil_mode=False):
    """(out, mask): max pool + flattened-argmax indices over the input's
    spatial dims (reference return_mask contract — the mask feeds
    max_unpool)."""
    import itertools

    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)

    def f(a):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        sp = a.shape[2:]
        ex = tuple(_ceil_extra(int(sp[i]), k[i], st[i], pd[i]) if ceil_mode
                   else 0 for i in range(n))
        ap = jnp.pad(a, ((0, 0), (0, 0)) + tuple(
            (p, p + e) for p, e in zip(pd, ex)),
            constant_values=_max_init(a.dtype))
        out_sp = tuple((ap.shape[2 + i] - k[i]) // st[i] + 1
                       for i in range(n))
        patches, flat_idx = [], []
        for offs in itertools.product(*[range(ki) for ki in k]):
            sl = ap[(slice(None), slice(None)) + tuple(
                slice(offs[i], offs[i] + out_sp[i] * st[i], st[i])
                for i in range(n))]
            patches.append(sl)
            idx = jnp.zeros((1, 1) + (1,) * n, jnp.int32)
            for i in range(n):
                # clamp padding-margin taps into the valid input extent so
                # a fully-padded window cannot emit a wrapped scatter index
                pos = jnp.clip(jnp.arange(out_sp[i]) * st[i] + offs[i]
                               - pd[i], 0, sp[i] - 1)
                shape = [1, 1] + [1] * n
                shape[2 + i] = out_sp[i]
                idx = idx * sp[i] + pos.reshape(shape)
            flat_idx.append(jnp.broadcast_to(idx, sl.shape))
        stacked = jnp.stack(patches, 0)             # [K, N, C, *out]
        arg = jnp.argmax(stacked, axis=0)
        out = jnp.max(stacked, axis=0)
        mask = jnp.take_along_axis(jnp.stack(flat_idx, 0), arg[None], 0)[0]
        # a window entirely in the pad margin has no valid argmax: the
        # reference kernel leaves its index at -1. Validity is static
        # geometry (does the window intersect the real extent?), never a
        # value comparison — dtype-min/-inf data maxima must keep their
        # real index.
        for i in range(n):
            starts = np.arange(out_sp[i]) * st[i] - pd[i]
            valid_i = (starts < sp[i]) & (starts + k[i] > 0)
            if valid_i.all():
                continue
            shape = [1, 1] + [1] * n
            shape[2 + i] = out_sp[i]
            mask = jnp.where(jnp.asarray(valid_i).reshape(shape), mask, -1)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            mask = jnp.moveaxis(mask, 1, -1)
        return out, mask.astype(jnp.int32)

    from ...autograd.function import apply_multi
    return apply_multi(f, x, name=name)


def _max_unpool(x, indices, kernel, stride, padding, output_size, n,
                data_format, name):
    """Scatter pooled values back to their argmax positions (reference:
    max_unpool kernels; default out extent (in-1)*stride + k - 2*pad)."""
    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")

    def f(a, idx):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        nb, c = a.shape[:2]
        in_sp = a.shape[2:]
        if output_size is not None:
            os_ = tuple(int(s) for s in output_size)
            if len(os_) == n + 2:
                # full-shape spec: extract the spatial dims per layout
                os_ = os_[1:-1] if channel_last else os_[2:]
            if len(os_) != n:
                raise ValueError(f"output_size needs {n} spatial dims "
                                 f"(or the full shape), got {output_size}")
            out_sp = os_
        else:
            out_sp = tuple((in_sp[i] - 1) * st[i] + k[i] - 2 * pd[i]
                           for i in range(n))
        s_total = int(np.prod(out_sp))
        bi = jnp.arange(nb).reshape(nb, 1, 1)
        ci = jnp.arange(c).reshape(1, c, 1)
        mi = idx.reshape(nb, c, -1)
        vals = a.reshape(nb, c, -1)
        # route invalid (-1) indices from fully-padded ceil-mode windows
        # into a dump slot past the real extent, then slice it off
        mi = jnp.where(mi >= 0, mi, s_total)
        flat = jnp.zeros((nb, c, s_total + 1), a.dtype) \
            .at[bi, ci, mi].set(vals)[:, :, :s_total]
        out = flat.reshape((nb, c) + out_sp)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(f, x, indices, name=name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None) -> Tensor:
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 1, data_format, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None) -> Tensor:
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 2, data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None) -> Tensor:
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 3, data_format, "max_unpool3d")
