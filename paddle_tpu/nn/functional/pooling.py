"""Pooling functionals over lax.reduce_window (reference:
python/paddle/nn/functional/pooling.py)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd.function import apply

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
           "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
           "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d"]


def _max_init(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.asarray(-jnp.inf, dt)
    return jnp.asarray(jnp.iinfo(dt).min, dt)


def _tup(v, n):
    a = np.atleast_1d(v)
    if a.size == 1:
        a = np.repeat(a, n)
    return tuple(int(x) for x in a)


def _pool(x, kernel, stride, padding, n, channel_last, reducer, init, name,
          ceil_mode=False, count_include_pad=True, average=False):
    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + st + (1,)
        pads = ((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)

    def f(a):
        out = jax.lax.reduce_window(a, init(a.dtype), reducer, dims, strides, pads)
        if average:
            if count_include_pad:
                denom = float(np.prod(k))
                out = out / denom
            else:
                ones = jnp.ones_like(a)
                cnt = jax.lax.reduce_window(ones, jnp.zeros((), a.dtype),
                                            jax.lax.add, dims, strides, pads)
                out = out / cnt
        return out
    return apply(f, x, name=name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None) -> Tensor:
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   data_format == "NLC", "max_pool1d")
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 jax.lax.max, _max_init,
                 "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None) -> Tensor:
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   data_format == "NHWC", "max_pool2d")
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.max, _max_init,
                 "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None) -> Tensor:
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   data_format == "NDHWC", "max_pool3d")
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.max, _max_init,
                 "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool1d",
                 count_include_pad=not exclusive, average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool2d",
                 count_include_pad=not exclusive, average=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool3d",
                 count_include_pad=not exclusive, average=True)


def _adaptive(x, output_size, n, channel_last, mode, name):
    out_sz = _tup(output_size, n)

    def f(a):
        sp_axes = list(range(2, 2 + n)) if not channel_last else \
            list(range(1, 1 + n))
        out = a
        for i, ax in enumerate(sp_axes):
            in_sz = out.shape[ax]
            o = out_sz[i]
            if in_sz % o == 0:
                k = in_sz // o
                shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(shape)
                out = jnp.max(r, axis=ax + 1) if mode == "max" else \
                    jnp.mean(r, axis=ax + 1)
            else:
                # general adaptive: gather variable windows
                starts = (np.arange(o) * in_sz) // o
                ends = ((np.arange(o) + 1) * in_sz + o - 1) // o
                slices = []
                for s_, e_ in zip(starts, ends):
                    w = jnp.take(out, jnp.arange(s_, e_), axis=ax)
                    red = jnp.max(w, axis=ax, keepdims=True) if mode == "max" \
                        else jnp.mean(w, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out
    return apply(f, x, name=name)


def adaptive_avg_pool1d(x, output_size, name=None) -> Tensor:
    return _adaptive(x, output_size, 1, False, "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None) -> Tensor:
    return _adaptive(x, output_size, 2, data_format == "NHWC", "avg",
                     "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None) -> Tensor:
    return _adaptive(x, output_size, 3, data_format == "NDHWC", "avg",
                     "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 1, False, "max", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 2, False, "max", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 3, False, "max", "adaptive_max_pool3d")


def _max_pool_with_mask(x, kernel, stride, padding, n, channel_last, name):
    """(out, mask): max pool + flattened-argmax indices over the input's
    spatial dims (reference return_mask contract — the mask feeds
    max_unpool)."""
    import itertools

    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)

    def f(a):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        sp = a.shape[2:]
        ap = jnp.pad(a, ((0, 0), (0, 0)) + tuple((p, p) for p in pd),
                     constant_values=_max_init(a.dtype))
        out_sp = tuple((ap.shape[2 + i] - k[i]) // st[i] + 1
                       for i in range(n))
        patches, flat_idx = [], []
        for offs in itertools.product(*[range(ki) for ki in k]):
            sl = ap[(slice(None), slice(None)) + tuple(
                slice(offs[i], offs[i] + out_sp[i] * st[i], st[i])
                for i in range(n))]
            patches.append(sl)
            idx = jnp.zeros((1, 1) + (1,) * n, jnp.int32)
            for i in range(n):
                pos = jnp.arange(out_sp[i]) * st[i] + offs[i] - pd[i]
                shape = [1, 1] + [1] * n
                shape[2 + i] = out_sp[i]
                idx = idx * sp[i] + pos.reshape(shape)
            flat_idx.append(jnp.broadcast_to(idx, sl.shape))
        stacked = jnp.stack(patches, 0)             # [K, N, C, *out]
        arg = jnp.argmax(stacked, axis=0)
        out = jnp.max(stacked, axis=0)
        mask = jnp.take_along_axis(jnp.stack(flat_idx, 0), arg[None], 0)[0]
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            mask = jnp.moveaxis(mask, 1, -1)
        return out, mask.astype(jnp.int32)

    from ...autograd.function import apply_multi
    return apply_multi(f, x, name=name)


def _max_unpool(x, indices, kernel, stride, padding, output_size, n,
                data_format, name):
    """Scatter pooled values back to their argmax positions (reference:
    max_unpool kernels; default out extent (in-1)*stride + k - 2*pad)."""
    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")

    def f(a, idx):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
            idx = jnp.moveaxis(idx, -1, 1)
        nb, c = a.shape[:2]
        in_sp = a.shape[2:]
        if output_size is not None:
            os_ = tuple(int(s) for s in output_size)
            if len(os_) == n + 2:
                # full-shape spec: extract the spatial dims per layout
                os_ = os_[1:-1] if channel_last else os_[2:]
            if len(os_) != n:
                raise ValueError(f"output_size needs {n} spatial dims "
                                 f"(or the full shape), got {output_size}")
            out_sp = os_
        else:
            out_sp = tuple((in_sp[i] - 1) * st[i] + k[i] - 2 * pd[i]
                           for i in range(n))
        s_total = int(np.prod(out_sp))
        bi = jnp.arange(nb).reshape(nb, 1, 1)
        ci = jnp.arange(c).reshape(1, c, 1)
        mi = idx.reshape(nb, c, -1)
        vals = a.reshape(nb, c, -1)
        flat = jnp.zeros((nb, c, s_total), a.dtype).at[bi, ci, mi].set(vals)
        out = flat.reshape((nb, c) + out_sp)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return apply(f, x, indices, name=name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None) -> Tensor:
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 1, data_format, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None) -> Tensor:
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 2, data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None) -> Tensor:
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 3, data_format, "max_unpool3d")
