"""Pooling functionals over lax.reduce_window (reference:
python/paddle/nn/functional/pooling.py)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd.function import apply

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
           "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
           "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d"]


def _max_init(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.asarray(-jnp.inf, dt)
    return jnp.asarray(jnp.iinfo(dt).min, dt)


def _tup(v, n):
    a = np.atleast_1d(v)
    if a.size == 1:
        a = np.repeat(a, n)
    return tuple(int(x) for x in a)


def _pool(x, kernel, stride, padding, n, channel_last, reducer, init, name,
          ceil_mode=False, count_include_pad=True, average=False):
    k = _tup(kernel, n)
    st = _tup(stride if stride is not None else kernel, n)
    pd = _tup(padding, n)
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + st + (1,)
        pads = ((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)

    def f(a):
        out = jax.lax.reduce_window(a, init(a.dtype), reducer, dims, strides, pads)
        if average:
            if count_include_pad:
                denom = float(np.prod(k))
                out = out / denom
            else:
                ones = jnp.ones_like(a)
                cnt = jax.lax.reduce_window(ones, jnp.zeros((), a.dtype),
                                            jax.lax.add, dims, strides, pads)
                out = out / cnt
        return out
    return apply(f, x, name=name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 jax.lax.max, _max_init,
                 "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.max, _max_init,
                 "max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.max, _max_init,
                 "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool1d",
                 count_include_pad=not exclusive, average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool2d",
                 count_include_pad=not exclusive, average=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None) -> Tensor:
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.add, lambda dt: jnp.zeros((), dt), "avg_pool3d",
                 count_include_pad=not exclusive, average=True)


def _adaptive(x, output_size, n, channel_last, mode, name):
    out_sz = _tup(output_size, n)

    def f(a):
        sp_axes = list(range(2, 2 + n)) if not channel_last else \
            list(range(1, 1 + n))
        out = a
        for i, ax in enumerate(sp_axes):
            in_sz = out.shape[ax]
            o = out_sz[i]
            if in_sz % o == 0:
                k = in_sz // o
                shape = out.shape[:ax] + (o, k) + out.shape[ax + 1:]
                r = out.reshape(shape)
                out = jnp.max(r, axis=ax + 1) if mode == "max" else \
                    jnp.mean(r, axis=ax + 1)
            else:
                # general adaptive: gather variable windows
                starts = (np.arange(o) * in_sz) // o
                ends = ((np.arange(o) + 1) * in_sz + o - 1) // o
                slices = []
                for s_, e_ in zip(starts, ends):
                    w = jnp.take(out, jnp.arange(s_, e_), axis=ax)
                    red = jnp.max(w, axis=ax, keepdims=True) if mode == "max" \
                        else jnp.mean(w, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out
    return apply(f, x, name=name)


def adaptive_avg_pool1d(x, output_size, name=None) -> Tensor:
    return _adaptive(x, output_size, 1, False, "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None) -> Tensor:
    return _adaptive(x, output_size, 2, data_format == "NHWC", "avg",
                     "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None) -> Tensor:
    return _adaptive(x, output_size, 3, data_format == "NDHWC", "avg",
                     "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 1, False, "max", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 2, False, "max", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None) -> Tensor:
    return _adaptive(x, output_size, 3, False, "max", "adaptive_max_pool3d")
