// Native serving engine over the PJRT C API (reference:
// paddle/fluid/inference/api/analysis_predictor.cc + capi_exp/ — the C++
// AnalysisPredictor and its C API).
//
// TPU-native realization: the deploy artifact is a StableHLO program
// (serialized by paddle_tpu.inference.export_native); this engine dlopens a
// PJRT plugin (libtpu.so on TPU hosts), compiles the program through
// PJRT_Client_Compile, and serves PJRT_LoadedExecutable_Execute round trips
// without any Python in the loop. The fake plugin (fake_pjrt_plugin.cc)
// stands in for hardware in CI the same way the reference tests its device
// ABI with a fake device (paddle/phi/backends/custom/fake_cpu_device.h).
//
// Exposed as a plain C API (ptpu_*) for ctypes binding and for embedding in
// C/C++ serving processes (reference capi_exp contract).

#include <dlfcn.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

struct Engine {
  void* dso = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  PJRT_LoadedExecutable* exec = nullptr;
  PJRT_Device* device = nullptr;
  std::string platform;
  std::string last_error;
  // outputs captured after each execute (engine-owned; callers copy out)
  std::vector<std::vector<int64_t>> out_dims;
  std::vector<int> out_types;
  std::vector<std::vector<char>> out_bytes;
};

void set_err(Engine* e, const std::string& msg) { e->last_error = msg; }

// Consume a PJRT_Error: record its message and destroy it. Returns true if
// there was an error.
bool take_error(Engine* e, PJRT_Error* err, const char* where) {
  if (err == nullptr) return false;
  std::string msg = where;
  msg += ": ";
  if (e->api && e->api->PJRT_Error_Message) {
    PJRT_Error_Message_Args margs;
    memset(&margs, 0, sizeof(margs));
    margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
    margs.error = err;
    e->api->PJRT_Error_Message(&margs);
    msg.append(margs.message, margs.message_size);
  } else {
    msg += "(no error introspection)";
  }
  if (e->api && e->api->PJRT_Error_Destroy) {
    PJRT_Error_Destroy_Args dargs;
    memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
    dargs.error = err;
    e->api->PJRT_Error_Destroy(&dargs);
  }
  set_err(e, msg);
  return true;
}

bool await_event(Engine* e, PJRT_Event* ev, const char* where) {
  if (ev == nullptr) return true;
  bool ok = true;
  if (e->api->PJRT_Event_Await) {
    PJRT_Event_Await_Args aargs;
    memset(&aargs, 0, sizeof(aargs));
    aargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
    aargs.event = ev;
    ok = !take_error(e, e->api->PJRT_Event_Await(&aargs), where);
  }
  if (e->api->PJRT_Event_Destroy) {
    PJRT_Event_Destroy_Args dargs;
    memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
    dargs.event = ev;
    e->api->PJRT_Event_Destroy(&dargs);
  }
  return ok;
}

void destroy_buffer(Engine* e, PJRT_Buffer* b) {
  if (!b || !e->api->PJRT_Buffer_Destroy) return;
  PJRT_Buffer_Destroy_Args args;
  memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
  args.buffer = b;
  take_error(e, e->api->PJRT_Buffer_Destroy(&args), "PJRT_Buffer_Destroy");
}

}  // namespace

extern "C" {

typedef struct Engine PtpuEngine;

// Load `plugin_path` (a PJRT plugin .so, e.g. libtpu.so), resolve GetPjrtApi,
// version-check, initialize the plugin, and create a client.
PtpuEngine* ptpu_create(const char* plugin_path) {
  Engine* e = new Engine();
  e->dso = dlopen(plugin_path, RTLD_NOW | RTLD_LOCAL);
  if (!e->dso) {
    set_err(e, std::string("dlopen failed: ") + dlerror());
    return e;
  }
  typedef const PJRT_Api* (*GetApiFn)();
  GetApiFn get_api =
      reinterpret_cast<GetApiFn>(dlsym(e->dso, "GetPjrtApi"));
  if (!get_api) {
    set_err(e, "plugin does not export GetPjrtApi");
    return e;
  }
  e->api = get_api();
  if (!e->api) {
    set_err(e, "GetPjrtApi returned null");
    return e;
  }
  if (e->api->pjrt_api_version.major_version != PJRT_API_MAJOR) {
    char buf[128];
    snprintf(buf, sizeof(buf),
             "PJRT ABI major mismatch: plugin %d, host %d",
             e->api->pjrt_api_version.major_version, PJRT_API_MAJOR);
    set_err(e, buf);
    return e;
  }
  if (e->api->PJRT_Plugin_Initialize) {
    PJRT_Plugin_Initialize_Args iargs;
    memset(&iargs, 0, sizeof(iargs));
    iargs.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    if (take_error(e, e->api->PJRT_Plugin_Initialize(&iargs),
                   "PJRT_Plugin_Initialize"))
      return e;
  }
  PJRT_Client_Create_Args cargs;
  memset(&cargs, 0, sizeof(cargs));
  cargs.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  if (take_error(e, e->api->PJRT_Client_Create(&cargs),
                 "PJRT_Client_Create"))
    return e;
  e->client = cargs.client;

  PJRT_Client_PlatformName_Args pargs;
  memset(&pargs, 0, sizeof(pargs));
  pargs.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
  pargs.client = e->client;
  if (!take_error(e, e->api->PJRT_Client_PlatformName(&pargs),
                  "PJRT_Client_PlatformName"))
    e->platform.assign(pargs.platform_name, pargs.platform_name_size);

  PJRT_Client_AddressableDevices_Args dargs;
  memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  dargs.client = e->client;
  if (take_error(e, e->api->PJRT_Client_AddressableDevices(&dargs),
                 "PJRT_Client_AddressableDevices"))
    return e;
  if (dargs.num_addressable_devices == 0) {
    set_err(e, "no addressable devices");
    return e;
  }
  e->device = dargs.addressable_devices[0];
  e->last_error.clear();
  return e;
}

// 1 when the engine is ready (client created, no pending error).
int ptpu_ok(PtpuEngine* e) {
  return e && e->client && e->last_error.empty() ? 1 : 0;
}

const char* ptpu_last_error(PtpuEngine* e) {
  return e ? e->last_error.c_str() : "null engine";
}

const char* ptpu_platform(PtpuEngine* e) { return e->platform.c_str(); }

int ptpu_api_minor(PtpuEngine* e) {
  return e && e->api ? e->api->pjrt_api_version.minor_version : -1;
}

// Compile an MLIR (StableHLO) module. `copts` is a serialized
// xla.CompileOptionsProto (produced at export time by the Python side so this
// engine never links protobuf).
int ptpu_compile(PtpuEngine* e, const char* mlir, size_t mlir_len,
                 const char* copts, size_t copts_len) {
  // non-fatal errors recorded by earlier calls (buffer destroy /
  // introspection) must not brick a healthy engine
  if (e && e->client) e->last_error.clear();
  if (!ptpu_ok(e)) return -1;
  PJRT_Program prog;
  memset(&prog, 0, sizeof(prog));
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = const_cast<char*>(mlir);
  prog.code_size = mlir_len;
  static const char kFormat[] = "mlir";
  prog.format = kFormat;
  prog.format_size = sizeof(kFormat) - 1;

  PJRT_Client_Compile_Args args;
  memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  args.client = e->client;
  args.program = &prog;
  args.compile_options = copts;
  args.compile_options_size = copts_len;
  if (take_error(e, e->api->PJRT_Client_Compile(&args),
                 "PJRT_Client_Compile"))
    return -1;
  e->exec = args.executable;
  return 0;
}

// Number of outputs of the compiled program, or -1 when the plugin does not
// implement executable introspection (the fake test plugin; callers then rely
// on the deploy container's output specs).
int ptpu_num_outputs(PtpuEngine* e) {
  if (!e || !e->exec) return -1;
  if (!e->api->PJRT_LoadedExecutable_GetExecutable ||
      !e->api->PJRT_Executable_NumOutputs)
    return -1;
  PJRT_LoadedExecutable_GetExecutable_Args gargs;
  memset(&gargs, 0, sizeof(gargs));
  gargs.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  gargs.loaded_executable = e->exec;
  if (take_error(e, e->api->PJRT_LoadedExecutable_GetExecutable(&gargs),
                 "PJRT_LoadedExecutable_GetExecutable"))
    return -1;
  PJRT_Executable_NumOutputs_Args nargs;
  memset(&nargs, 0, sizeof(nargs));
  nargs.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  nargs.executable = gargs.executable;
  if (take_error(e, e->api->PJRT_Executable_NumOutputs(&nargs),
                 "PJRT_Executable_NumOutputs"))
    return -1;
  return static_cast<int>(nargs.num_outputs);
}

// Execute one inference. Inputs are dense host buffers in major-to-minor
// layout; outputs are copied into engine-owned storage, readable through the
// ptpu_output_* accessors until the next execute.
//
// dtypes use PJRT_Buffer_Type codes. Returns 0 on success.
int ptpu_execute(PtpuEngine* e, int num_args, const void** data,
                 const int* dtypes, const int64_t* dims_flat,
                 const int* ndims, int num_outputs) {
  if (e && e->client) e->last_error.clear();
  if (!ptpu_ok(e) || !e->exec) {
    if (e && e->last_error.empty()) set_err(e, "no compiled program");
    return -1;
  }
  std::vector<PJRT_Buffer*> in_bufs(num_args, nullptr);
  const int64_t* dcur = dims_flat;
  for (int i = 0; i < num_args; ++i) {
    PJRT_Client_BufferFromHostBuffer_Args bargs;
    memset(&bargs, 0, sizeof(bargs));
    bargs.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    bargs.client = e->client;
    bargs.data = data[i];
    bargs.type = static_cast<PJRT_Buffer_Type>(dtypes[i]);
    bargs.dims = dcur;
    bargs.num_dims = ndims[i];
    dcur += ndims[i];
    // data is fully copied before the call returns, so host buffers need no
    // lifetime coupling to the device buffer
    bargs.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableOnlyDuringCall;
    bargs.device = e->device;
    if (take_error(e, e->api->PJRT_Client_BufferFromHostBuffer(&bargs),
                   "PJRT_Client_BufferFromHostBuffer")) {
      for (auto* b : in_bufs) destroy_buffer(e, b);
      return -1;
    }
    in_bufs[i] = bargs.buffer;
    if (!await_event(e, bargs.done_with_host_buffer, "h2d event")) {
      for (auto* b : in_bufs) destroy_buffer(e, b);
      return -1;
    }
  }

  PJRT_ExecuteOptions opts;
  memset(&opts, 0, sizeof(opts));
  opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

  std::vector<PJRT_Buffer*> outs(num_outputs, nullptr);
  PJRT_Buffer** out_list = outs.data();
  PJRT_Buffer* const* arg_list = in_bufs.data();
  PJRT_Event* done = nullptr;

  PJRT_LoadedExecutable_Execute_Args eargs;
  memset(&eargs, 0, sizeof(eargs));
  eargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  eargs.executable = e->exec;
  eargs.options = &opts;
  eargs.argument_lists = &arg_list;
  eargs.num_devices = 1;
  eargs.num_args = num_args;
  eargs.output_lists = &out_list;
  eargs.device_complete_events = &done;
  eargs.execute_device = e->device;
  bool fail = take_error(e, e->api->PJRT_LoadedExecutable_Execute(&eargs),
                         "PJRT_LoadedExecutable_Execute");
  for (auto* b : in_bufs) destroy_buffer(e, b);
  if (!fail) fail = !await_event(e, done, "execute event");
  if (fail) {
    for (auto* b : outs) destroy_buffer(e, b);
    return -1;
  }

  e->out_dims.assign(num_outputs, {});
  e->out_types.assign(num_outputs, 0);
  e->out_bytes.assign(num_outputs, {});
  int rc = 0;
  for (int i = 0; i < num_outputs && rc == 0; ++i) {
    // buffer introspection is OPTIONAL: out_types[i] stays 0 (INVALID) on
    // a missing or failing plugin entry, and the binding falls back to the
    // deploy container's output specs. Failures here must not poison
    // last_error for the (successful) execute, so errors are consumed
    // into a scratch slot.
    std::string saved_err;
    std::swap(saved_err, e->last_error);
    bool dims_ok = false;
    if (e->api->PJRT_Buffer_Dimensions) {
      PJRT_Buffer_Dimensions_Args dims_args;
      memset(&dims_args, 0, sizeof(dims_args));
      dims_args.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
      dims_args.buffer = outs[i];
      if (!take_error(e, e->api->PJRT_Buffer_Dimensions(&dims_args),
                      "PJRT_Buffer_Dimensions")) {
        e->out_dims[i].assign(dims_args.dims,
                              dims_args.dims + dims_args.num_dims);
        dims_ok = true;
      }
    }
    if (dims_ok && e->api->PJRT_Buffer_ElementType) {
      PJRT_Buffer_ElementType_Args et_args;
      memset(&et_args, 0, sizeof(et_args));
      et_args.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
      et_args.buffer = outs[i];
      if (!take_error(e, e->api->PJRT_Buffer_ElementType(&et_args),
                      "PJRT_Buffer_ElementType"))
        e->out_types[i] = static_cast<int>(et_args.type);
    }
    // out_types[i] stays 0 (INVALID) unless BOTH dims and dtype were
    // introspected — a dtype without a shape would make the binding
    // reshape to (), so partial metadata falls back to container specs
    std::swap(saved_err, e->last_error);

    PJRT_Buffer_ToHostBuffer_Args hargs;
    memset(&hargs, 0, sizeof(hargs));
    hargs.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    hargs.src = outs[i];
    hargs.dst = nullptr;  // size query
    if (take_error(e, e->api->PJRT_Buffer_ToHostBuffer(&hargs),
                   "PJRT_Buffer_ToHostBuffer(size)")) {
      rc = -1;
      break;
    }
    e->out_bytes[i].resize(hargs.dst_size);
    hargs.dst = e->out_bytes[i].data();
    // dst_size keeps the required size from the query
    if (take_error(e, e->api->PJRT_Buffer_ToHostBuffer(&hargs),
                   "PJRT_Buffer_ToHostBuffer"))
      rc = -1;
    else if (!await_event(e, hargs.event, "d2h event"))
      rc = -1;
  }
  for (auto* b : outs) destroy_buffer(e, b);
  return rc;
}

size_t ptpu_output_nbytes(PtpuEngine* e, int i) {
  if (!e || i < 0 || i >= (int)e->out_bytes.size()) return 0;
  return e->out_bytes[i].size();
}

int ptpu_output_copy(PtpuEngine* e, int i, void* dst, size_t cap) {
  if (!e || i < 0 || i >= (int)e->out_bytes.size()) return -1;
  if (cap < e->out_bytes[i].size()) return -1;
  memcpy(dst, e->out_bytes[i].data(), e->out_bytes[i].size());
  return 0;
}

int ptpu_output_ndim(PtpuEngine* e, int i) {
  if (!e || i < 0 || i >= (int)e->out_dims.size()) return -1;
  return (int)e->out_dims[i].size();
}

int64_t ptpu_output_dim(PtpuEngine* e, int i, int d) {
  if (!e || i < 0 || i >= (int)e->out_dims.size()) return -1;
  if (d < 0 || d >= (int)e->out_dims[i].size()) return -1;
  return e->out_dims[i][d];
}

int ptpu_output_dtype(PtpuEngine* e, int i) {
  if (!e || i < 0 || i >= (int)e->out_types.size()) return -1;
  return e->out_types[i];
}

void ptpu_destroy(PtpuEngine* e) {
  if (!e) return;
  if (e->api) {
    if (e->exec && e->api->PJRT_LoadedExecutable_Destroy) {
      PJRT_LoadedExecutable_Destroy_Args args;
      memset(&args, 0, sizeof(args));
      args.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
      args.executable = e->exec;
      e->api->PJRT_LoadedExecutable_Destroy(&args);
    }
    if (e->client && e->api->PJRT_Client_Destroy) {
      PJRT_Client_Destroy_Args args;
      memset(&args, 0, sizeof(args));
      args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
      args.client = e->client;
      e->api->PJRT_Client_Destroy(&args);
    }
  }
  if (e->dso) dlclose(e->dso);
  delete e;
}

}  // extern "C"
