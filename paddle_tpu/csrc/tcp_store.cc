// Native TCP key-value store server: the control-plane rendezvous service
// behind init_parallel_env and the object collectives.
//
// Reference analog: paddle/phi/core/distributed/store/tcp_store.h — the
// reference's TCPStore master is native C++ serving blocking get/add/wait
// over a length-prefixed socket protocol; this is the same component for
// the TPU build. The Python TCPStore (distributed/store.py) speaks the
// identical binary protocol and remains the no-toolchain fallback server;
// values are opaque bytes (the Python client pickles them), counters are
// explicit int64s, so nothing here parses Python objects.
//
// Wire protocol (all integers big-endian):
//   request :=  u32 len | u8 op | u16 keylen | key | i64 ival | f64 timeout
//               | u32 vlen | value
//   ops: 1=set 2=get 3=add 4=wait_ge 5=delete 6=delete_prefix
//   reply   :=  u32 len | u8 ok | u8 kind | payload
//   kinds: 0=none 1=int(i64) 2=bytes(u32+data); ok=0 carries kind=2 error
//
// Concurrency: accept thread + one detached thread per connection (the
// client holds a persistent socket), one mutex + condvar over the map for
// the blocking get/wait_ge primitives. Pure C++17 + POSIX sockets.
//
// C API (ctypes, distributed/store.py):
//   void*  tcp_store_server_start(const char* host, int port, int* out)
//   void   tcp_store_server_stop(void*)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Value {
  bool is_int = false;
  int64_t i = 0;
  std::string bytes;
};

struct Server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::mutex mu;
  std::condition_variable cv;        // data changes + shutdown wakeups
  std::condition_variable drain_cv;  // connection-thread exit
  std::map<std::string, Value> data;
  std::map<int, bool> conn_fds;      // live connection sockets
  int conns = 0;
  bool stopping = false;
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

uint64_t be64(const unsigned char* p) {
  uint64_t v = 0;
  for (int k = 0; k < 8; ++k) v = (v << 8) | p[k];
  return v;
}

void put_be(std::string* out, uint64_t v, int nbytes) {
  for (int k = nbytes - 1; k >= 0; --k)
    out->push_back(static_cast<char>((v >> (8 * k)) & 0xff));
}

bool send_reply(int fd, bool ok, int kind, int64_t ival,
                const std::string& bytes) {
  std::string body;
  body.push_back(ok ? 1 : 0);
  body.push_back(static_cast<char>(kind));
  if (kind == 1) {
    put_be(&body, static_cast<uint64_t>(ival), 8);
  } else if (kind == 2) {
    put_be(&body, bytes.size(), 4);
    body += bytes;
  }
  std::string frame;
  put_be(&frame, body.size(), 4);
  frame += body;
  return write_exact(fd, frame.data(), frame.size());
}

bool send_err(int fd, const std::string& msg) {
  return send_reply(fd, false, 2, 0, msg);
}

void handle_conn(Server* s, int fd) {
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->stopping) {
      ::close(fd);
      --s->conns;
      s->drain_cv.notify_all();
      return;
    }
    s->conn_fds[fd] = true;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<unsigned char> buf;
  for (;;) {
    unsigned char hdr[4];
    if (!read_exact(fd, hdr, 4)) break;
    uint32_t len = (hdr[0] << 24) | (hdr[1] << 16) | (hdr[2] << 8) | hdr[3];
    if (len < 1 + 2 + 8 + 8 + 4 || len > (1u << 30)) {
      // malformed or absurd frame: the stream cannot be resynced, but the
      // client deserves a reply before the close (post-send failures are
      // not retried), not a silent ConnectionError
      send_err(fd, "store frame rejected (malformed or >1GB)");
      break;
    }
    buf.resize(len);
    if (!read_exact(fd, buf.data(), len)) break;
    const unsigned char* p = buf.data();
    int op = *p++;
    uint16_t keylen = (p[0] << 8) | p[1];
    p += 2;
    if (1u + 2 + keylen + 8 + 8 + 4 > len) break;
    std::string key(reinterpret_cast<const char*>(p), keylen);
    p += keylen;
    int64_t ival = static_cast<int64_t>(be64(p));
    p += 8;
    uint64_t tbits = be64(p);
    p += 8;
    double timeout;
    std::memcpy(&timeout, &tbits, 8);
    uint32_t vlen = (p[0] << 24) | (p[1] << 16) | (p[2] << 8) | p[3];
    p += 4;
    if (1u + 2 + keylen + 8 + 8 + 4 + vlen != len) break;
    std::string value(reinterpret_cast<const char*>(p), vlen);

    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<
                        std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(timeout));
    // Compute the reply under the lock, SEND it after release: a stalled
    // client with a full receive window must only wedge its own
    // connection thread, never the store mutex (cluster-wide rendezvous
    // rides this one lock).
    bool ok = false;
    int kind = 0;
    int64_t rint = 0;
    std::string rbytes;
    switch (op) {
      case 1: {  // set
        std::lock_guard<std::mutex> g(s->mu);
        Value v;
        v.bytes = std::move(value);
        s->data[key] = std::move(v);
        s->cv.notify_all();
        ok = true;
        break;
      }
      case 2: {  // get (blocks until the key exists)
        std::unique_lock<std::mutex> g(s->mu);
        bool present = s->cv.wait_until(g, deadline, [&] {
          return s->stopping || s->data.count(key) > 0;
        });
        if (present && !s->stopping && s->data.count(key)) {
          const Value& v = s->data[key];
          ok = true;
          if (v.is_int) {
            kind = 1;
            rint = v.i;
          } else {
            kind = 2;
            rbytes = v.bytes;  // copy under lock; send after
          }
        } else {
          kind = 2;
          rbytes = "store get('" + key + "') timed out";
        }
        break;
      }
      case 3: {  // add
        std::lock_guard<std::mutex> g(s->mu);
        Value& v = s->data[key];
        if (!v.is_int && !v.bytes.empty()) {
          kind = 2;
          rbytes = "store add on non-counter key '" + key + "'";
          break;
        }
        v.is_int = true;
        v.i += ival;
        s->cv.notify_all();
        ok = true;
        kind = 1;
        rint = v.i;
        break;
      }
      case 4: {  // wait_ge
        std::unique_lock<std::mutex> g(s->mu);
        bool reached = s->cv.wait_until(g, deadline, [&] {
          if (s->stopping) return true;
          auto it = s->data.find(key);
          return it != s->data.end() && it->second.is_int &&
                 it->second.i >= ival;
        });
        auto it = s->data.find(key);
        if (reached && !s->stopping && it != s->data.end() &&
            it->second.is_int && it->second.i >= ival) {
          ok = true;
          kind = 1;
          rint = it->second.i;
        } else {
          kind = 2;
          rbytes = "store wait_ge('" + key + "') timed out";
        }
        break;
      }
      case 5: {  // delete
        std::lock_guard<std::mutex> g(s->mu);
        ok = true;
        kind = 1;
        rint = static_cast<int64_t>(s->data.erase(key));
        break;
      }
      case 6: {  // delete_prefix
        std::lock_guard<std::mutex> g(s->mu);
        int64_t n = 0;
        for (auto it = s->data.lower_bound(key); it != s->data.end();) {
          if (it->first.compare(0, key.size(), key) != 0) break;
          it = s->data.erase(it);
          ++n;
        }
        ok = true;
        kind = 1;
        rint = n;
        break;
      }
      default:
        kind = 2;
        rbytes = "unknown store op";
    }
    if (!send_reply(fd, ok, kind, rint, rbytes)) break;
  }
  ::close(fd);
  std::lock_guard<std::mutex> g(s->mu);
  s->conn_fds.erase(fd);
  --s->conns;
  s->drain_cv.notify_all();
}

void accept_loop(Server* s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      std::lock_guard<std::mutex> g(s->mu);
      if (s->stopping) return;
      continue;
    }
    {
      std::lock_guard<std::mutex> g(s->mu);
      ++s->conns;
    }
    std::thread(handle_conn, s, fd).detach();
  }
}

}  // namespace

extern "C" {

void* tcp_store_server_start(const char* host, int port, int* port_out) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (!host || !*host || std::strcmp(host, "0.0.0.0") == 0) {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    // hostname (e.g. "localhost"): bind wildcard — rendezvous servers
    // listen for every rank anyway, name resolution stays client-side
    addr.sin_addr.s_addr = INADDR_ANY;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (port_out) *port_out = ntohs(addr.sin_port);
  auto* s = new Server();
  s->listen_fd = fd;
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

void tcp_store_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return;
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->stopping = true;
    s->cv.notify_all();  // wake blocked get/wait_ge handlers
    for (auto& kv : s->conn_fds)
      ::shutdown(kv.first, SHUT_RDWR);  // unblock handlers parked in read()
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    // detached handler threads must all exit before the Server dies;
    // bounded wait so a wedged handler leaks the Server instead of
    // use-after-free-ing it
    std::unique_lock<std::mutex> g(s->mu);
    bool drained = s->drain_cv.wait_for(
        g, std::chrono::seconds(5), [&] { return s->conns == 0; });
    if (!drained) return;  // leak by design; process is tearing down
  }
  delete s;
}

}  // extern "C"
