// Native host-side ops exposed through the XLA FFI ABI.
//
// Reference analog: the custom-op C++ sources users build with
// paddle.utils.cpp_extension (custom_relu etc. in the reference test suite).
// These handlers run on the host platform; device kernels belong to Pallas.
//
// Build: paddle_tpu.utils.cpp_extension.load(name, [this file], functions=...)

#include <cmath>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

// out = x*x + y  (the canonical custom-op smoke test)
static ffi::Error SquareAddImpl(ffi::Buffer<ffi::F32> x,
                                ffi::Buffer<ffi::F32> y,
                                ffi::ResultBuffer<ffi::F32> out) {
  const float* xd = x.typed_data();
  const float* yd = y.typed_data();
  float* od = out->typed_data();
  const size_t n = x.element_count();
  for (size_t i = 0; i < n; ++i) od[i] = xd[i] * xd[i] + yd[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    SquareAdd, SquareAddImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

// Greedy byte-pair-free whitespace "tokenizer": maps bytes to ids with a
// trivial hash, writing fixed-length id rows — the host-side data-pipeline
// op class the extension mechanism exists for (no Python round trip).
static ffi::Error HashTokenizeImpl(ffi::Buffer<ffi::U8> text,
                                   ffi::ResultBuffer<ffi::S32> ids) {
  const uint8_t* t = text.typed_data();
  int32_t* o = ids->typed_data();
  const size_t n_in = text.element_count();
  const size_t n_out = ids->element_count();
  size_t w = 0;
  uint32_t h = 2166136261u;
  bool in_word = false;
  for (size_t i = 0; i < n_in && w < n_out; ++i) {
    const uint8_t c = t[i];
    if (c == ' ' || c == '\n' || c == '\t') {
      if (in_word) {
        o[w++] = static_cast<int32_t>(h % 50000);
        h = 2166136261u;
        in_word = false;
      }
    } else {
      h = (h ^ c) * 16777619u;
      in_word = true;
    }
  }
  if (in_word && w < n_out) o[w++] = static_cast<int32_t>(h % 50000);
  for (; w < n_out; ++w) o[w] = -1;  // pad
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    HashTokenize, HashTokenizeImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Ret<ffi::Buffer<ffi::S32>>());
