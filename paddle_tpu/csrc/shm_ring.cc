// Shared-memory bounded ring queue for the multiprocess DataLoader.
//
// Reference analog: paddle/fluid/framework/data_feed.cc + the reference
// DataLoader's _shared_memory transport (C++ shared-memory batch plane
// behind use_shared_memory=True). The Python fallback ships every batch
// through a multiprocessing.Queue (pipe write + pickle + per-batch
// SharedMemory create/unlink); this core maps ONE arena and moves batch
// bytes through a lock-free multi-producer/single-consumer bounded queue
// (Vyukov MPMC: per-slot sequence numbers, C++11 atomics — valid across
// processes on MAP_SHARED memory).
//
// Layout of the arena:
//   [Header][Slot 0][Slot 1]...[Slot n-1]
//   Slot = [atomic<u64> seq][u32 len][u8 payload[slot_bytes]]
//
// C ABI (driven from Python via ctypes; no pybind11 in this image):
//   shm_ring_bytes(slots, slot_bytes)        -> arena size to map
//   shm_ring_init(mem, slots, slot_bytes)    -> 0/-1
//   shm_ring_push(mem, data, len, spin_us)   -> 0 ok, -1 full-timeout,
//                                               -2 oversized
//   shm_ring_pop(mem, out, cap, spin_us)     -> payload len, -1 empty,
//                                               -2 cap too small
//
// Build: g++ -O2 -shared -fPIC shm_ring.cc -o libshm_ring.so  (pure
// C++17 + libc; loaded by paddle_tpu/io/shm_ring.py).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

namespace {

struct Header {
  uint32_t magic;
  uint32_t slots;       // power of two
  uint32_t slot_bytes;
  uint32_t pad_;
  std::atomic<uint64_t> enqueue_pos;
  std::atomic<uint64_t> dequeue_pos;
};

struct SlotHead {
  std::atomic<uint64_t> seq;
  uint32_t len;
  uint32_t pad_;
};

constexpr uint32_t kMagic = 0x52494e47;  // "RING"
constexpr size_t kAlign = 64;            // cache-line the slot heads

inline size_t slot_stride(uint32_t slot_bytes) {
  size_t raw = sizeof(SlotHead) + slot_bytes;
  return (raw + kAlign - 1) / kAlign * kAlign;
}

inline SlotHead* slot_at(Header* h, uint64_t idx) {
  auto* base = reinterpret_cast<uint8_t*>(h + 1);
  return reinterpret_cast<SlotHead*>(
      base + (idx & (h->slots - 1)) * slot_stride(h->slot_bytes));
}

inline void backoff(uint32_t spins) {
  // adaptive: 50us for the first ~5ms of waiting, then 1ms — long waits
  // (slow datasets, paused consumers) must not burn 20k syscalls/s
  long ns = spins < 100 ? 50 * 1000 : 1000 * 1000;
  timespec ts{0, ns};
  nanosleep(&ts, nullptr);
}

inline uint64_t now_us() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

}  // namespace

extern "C" {

size_t shm_ring_bytes(uint32_t slots, uint32_t slot_bytes) {
  return sizeof(Header) + static_cast<size_t>(slots) *
      slot_stride(slot_bytes);
}

int shm_ring_init(void* mem, uint32_t slots, uint32_t slot_bytes) {
  if (mem == nullptr || slots == 0 || (slots & (slots - 1)) != 0) return -1;
  auto* h = new (mem) Header();
  h->magic = kMagic;
  h->slots = slots;
  h->slot_bytes = slot_bytes;
  h->enqueue_pos.store(0, std::memory_order_relaxed);
  h->dequeue_pos.store(0, std::memory_order_relaxed);
  for (uint64_t i = 0; i < slots; ++i) {
    auto* s = slot_at(h, i);
    s->seq.store(i, std::memory_order_relaxed);
    s->len = 0;
  }
  std::atomic_thread_fence(std::memory_order_seq_cst);
  return 0;
}

int shm_ring_push(void* mem, const uint8_t* data, uint32_t len,
                  int64_t timeout_us) {
  auto* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) return -1;
  if (len > h->slot_bytes) return -2;
  const uint64_t deadline = timeout_us < 0 ? ~0ull : now_us() + timeout_us;
  uint64_t pos = h->enqueue_pos.load(std::memory_order_relaxed);
  uint32_t spins = 0;
  for (;;) {
    SlotHead* s = slot_at(h, pos);
    uint64_t seq = s->seq.load(std::memory_order_acquire);
    intptr_t diff = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
    if (diff == 0) {
      if (h->enqueue_pos.compare_exchange_weak(
              pos, pos + 1, std::memory_order_relaxed)) {
        std::memcpy(reinterpret_cast<uint8_t*>(s + 1), data, len);
        s->len = len;
        s->seq.store(pos + 1, std::memory_order_release);  // publish
        return 0;
      }
      // CAS lost: pos was refreshed by compare_exchange
    } else if (diff < 0) {
      if (now_us() >= deadline) return -1;  // full
      backoff(spins++);
      pos = h->enqueue_pos.load(std::memory_order_relaxed);
    } else {
      pos = h->enqueue_pos.load(std::memory_order_relaxed);
    }
  }
}

int shm_ring_pop(void* mem, uint8_t* out, uint32_t cap, int64_t timeout_us) {
  auto* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) return -1;
  const uint64_t deadline = timeout_us < 0 ? ~0ull : now_us() + timeout_us;
  uint64_t pos = h->dequeue_pos.load(std::memory_order_relaxed);
  uint32_t spins = 0;
  for (;;) {
    SlotHead* s = slot_at(h, pos);
    uint64_t seq = s->seq.load(std::memory_order_acquire);
    intptr_t diff = static_cast<intptr_t>(seq) -
        static_cast<intptr_t>(pos + 1);
    if (diff == 0) {
      if (h->dequeue_pos.compare_exchange_weak(
              pos, pos + 1, std::memory_order_relaxed)) {
        const uint32_t len = s->len;
        if (len > cap) {
          // roll back: the slot stays consumable
          h->dequeue_pos.store(pos, std::memory_order_relaxed);
          s->seq.store(seq, std::memory_order_release);
          return -2;
        }
        std::memcpy(out, reinterpret_cast<uint8_t*>(s + 1), len);
        s->seq.store(pos + h->slots, std::memory_order_release);  // free
        return static_cast<int>(len);
      }
    } else if (diff < 0) {
      if (now_us() >= deadline) return -1;  // empty
      backoff(spins++);
      pos = h->dequeue_pos.load(std::memory_order_relaxed);
    } else {
      pos = h->dequeue_pos.load(std::memory_order_relaxed);
    }
  }
}

}  // extern "C"
