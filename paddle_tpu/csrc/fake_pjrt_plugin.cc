// Fake PJRT plugin for testing the native serving engine without hardware
// (reference test pattern: paddle/phi/backends/custom/fake_cpu_device.h — a
// fake device that exercises the plugin ABI end to end in CI).
//
// Implements the minimal PJRT C API slice pjrt_predictor.cc touches:
// client create/destroy, one addressable device, compile (stores the program
// bytes), execute (identity: output[i] is a copy of input[i]), host<->device
// buffer copies, events (always ready). Compiled against the same
// pjrt_c_api.h as the engine, so struct-size discipline and the call
// protocol are validated for real; only the math is fake.

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

struct FakeError {
  std::string message;
};

struct FakeBuffer {
  std::vector<char> bytes;
  std::vector<int64_t> dims;
  PJRT_Buffer_Type type;
};

struct FakeExec {
  std::string program;
  std::string format;
};

// PJRT handles are opaque pointers; we reinterpret our own structs. A single
// static device handle marks "the" fake device.
int g_device_tag;
PJRT_Device* kDevice = reinterpret_cast<PJRT_Device*>(&g_device_tag);
int g_client_tag;

size_t type_size(PJRT_Buffer_Type t) {
  switch (t) {
    case PJRT_Buffer_Type_PRED:
    case PJRT_Buffer_Type_S8:
    case PJRT_Buffer_Type_U8:
      return 1;
    case PJRT_Buffer_Type_S16:
    case PJRT_Buffer_Type_U16:
    case PJRT_Buffer_Type_F16:
    case PJRT_Buffer_Type_BF16:
      return 2;
    case PJRT_Buffer_Type_S32:
    case PJRT_Buffer_Type_U32:
    case PJRT_Buffer_Type_F32:
      return 4;
    default:
      return 8;
  }
}

PJRT_Error* err(const char* msg) { return reinterpret_cast<PJRT_Error*>(new FakeError{msg}); }

// ---- error ----
void ErrorDestroy(PJRT_Error_Destroy_Args* a) {
  delete reinterpret_cast<FakeError*>(a->error);
}
void ErrorMessage(PJRT_Error_Message_Args* a) {
  auto* e = reinterpret_cast<const FakeError*>(a->error);
  a->message = e->message.c_str();
  a->message_size = e->message.size();
}
PJRT_Error* ErrorGetCode(PJRT_Error_GetCode_Args* a) {
  a->code = PJRT_Error_Code_INTERNAL;
  return nullptr;
}

// ---- plugin / events ----
PJRT_Error* PluginInitialize(PJRT_Plugin_Initialize_Args*) { return nullptr; }
PJRT_Error* EventDestroy(PJRT_Event_Destroy_Args*) { return nullptr; }
PJRT_Error* EventAwait(PJRT_Event_Await_Args*) { return nullptr; }
PJRT_Error* EventIsReady(PJRT_Event_IsReady_Args* a) {
  a->is_ready = true;
  return nullptr;
}

// ---- client ----
PJRT_Error* ClientCreate(PJRT_Client_Create_Args* a) {
  a->client = reinterpret_cast<PJRT_Client*>(&g_client_tag);
  return nullptr;
}
PJRT_Error* ClientDestroy(PJRT_Client_Destroy_Args*) { return nullptr; }
PJRT_Error* ClientPlatformName(PJRT_Client_PlatformName_Args* a) {
  static const char kName[] = "fake";
  a->platform_name = kName;
  a->platform_name_size = sizeof(kName) - 1;
  return nullptr;
}
PJRT_Error* ClientAddressableDevices(PJRT_Client_AddressableDevices_Args* a) {
  static PJRT_Device* devs[1] = {kDevice};
  a->addressable_devices = devs;
  a->num_addressable_devices = 1;
  return nullptr;
}
PJRT_Error* ClientCompile(PJRT_Client_Compile_Args* a) {
  auto* ex = new FakeExec();
  ex->program.assign(a->program->code, a->program->code_size);
  ex->format.assign(a->program->format, a->program->format_size);
  if (ex->format != "mlir")
    return err("fake plugin only accepts mlir programs");
  if (a->compile_options_size == 0)
    return err("missing serialized CompileOptionsProto");
  a->executable = reinterpret_cast<PJRT_LoadedExecutable*>(ex);
  return nullptr;
}

// ---- buffers ----
PJRT_Error* BufferFromHostBuffer(PJRT_Client_BufferFromHostBuffer_Args* a) {
  if (a->num_byte_strides != 0 && a->byte_strides != nullptr)
    return err("fake plugin requires dense major-to-minor input");
  auto* b = new FakeBuffer();
  b->type = a->type;
  b->dims.assign(a->dims, a->dims + a->num_dims);
  size_t n = type_size(a->type);
  for (size_t i = 0; i < a->num_dims; ++i) n *= (size_t)a->dims[i];
  b->bytes.resize(n);
  memcpy(b->bytes.data(), a->data, n);
  a->buffer = reinterpret_cast<PJRT_Buffer*>(b);
  a->done_with_host_buffer = nullptr;  // copied synchronously
  return nullptr;
}
PJRT_Error* BufferDestroy(PJRT_Buffer_Destroy_Args* a) {
  delete reinterpret_cast<FakeBuffer*>(a->buffer);
  return nullptr;
}
PJRT_Error* BufferDimensions(PJRT_Buffer_Dimensions_Args* a) {
  auto* b = reinterpret_cast<FakeBuffer*>(a->buffer);
  a->dims = b->dims.data();
  a->num_dims = b->dims.size();
  return nullptr;
}
PJRT_Error* BufferElementType(PJRT_Buffer_ElementType_Args* a) {
  a->type = reinterpret_cast<FakeBuffer*>(a->buffer)->type;
  return nullptr;
}
PJRT_Error* BufferToHostBuffer(PJRT_Buffer_ToHostBuffer_Args* a) {
  auto* b = reinterpret_cast<FakeBuffer*>(a->src);
  if (a->dst == nullptr) {
    a->dst_size = b->bytes.size();
    a->event = nullptr;
    return nullptr;
  }
  if (a->dst_size < b->bytes.size()) return err("dst too small");
  memcpy(a->dst, b->bytes.data(), b->bytes.size());
  a->event = nullptr;
  return nullptr;
}

// ---- execute ----
// The fake "compiles" every program to the same executable: ONE output that
// is a byte-exact copy of input 0. Both sides of the real contract size
// output_lists from the executable's output count, so the fake also reports
// NumOutputs == 1 through the introspection path.
PJRT_Error* ExecutableDestroy(PJRT_LoadedExecutable_Destroy_Args* a) {
  delete reinterpret_cast<FakeExec*>(a->executable);
  return nullptr;
}
PJRT_Error* GetExecutable(PJRT_LoadedExecutable_GetExecutable_Args* a) {
  a->executable =
      reinterpret_cast<PJRT_Executable*>(a->loaded_executable);
  return nullptr;
}
PJRT_Error* NumOutputs(PJRT_Executable_NumOutputs_Args* a) {
  a->num_outputs = 1;
  return nullptr;
}
PJRT_Error* Execute(PJRT_LoadedExecutable_Execute_Args* a) {
  if (a->num_devices != 1) return err("fake plugin is single-device");
  if (a->num_args == 0) return err("fake executable needs >= 1 input");
  auto* src = reinterpret_cast<FakeBuffer*>(a->argument_lists[0][0]);
  a->output_lists[0][0] =
      reinterpret_cast<PJRT_Buffer*>(new FakeBuffer(*src));
  if (a->device_complete_events) a->device_complete_events[0] = nullptr;
  return nullptr;
}

}  // namespace

extern "C" const PJRT_Api* GetPjrtApi() {
  static PJRT_Api api;
  memset(&api, 0, sizeof(api));
  api.struct_size = PJRT_Api_STRUCT_SIZE;
  api.pjrt_api_version.struct_size = PJRT_Api_Version_STRUCT_SIZE;
  api.pjrt_api_version.major_version = PJRT_API_MAJOR;
  api.pjrt_api_version.minor_version = PJRT_API_MINOR;
  api.PJRT_Error_Destroy = ErrorDestroy;
  api.PJRT_Error_Message = ErrorMessage;
  api.PJRT_Error_GetCode = ErrorGetCode;
  api.PJRT_Plugin_Initialize = PluginInitialize;
  api.PJRT_Event_Destroy = EventDestroy;
  api.PJRT_Event_IsReady = EventIsReady;
  api.PJRT_Event_Await = EventAwait;
  api.PJRT_Client_Create = ClientCreate;
  api.PJRT_Client_Destroy = ClientDestroy;
  api.PJRT_Client_PlatformName = ClientPlatformName;
  api.PJRT_Client_AddressableDevices = ClientAddressableDevices;
  api.PJRT_Client_Compile = ClientCompile;
  api.PJRT_Client_BufferFromHostBuffer = BufferFromHostBuffer;
  api.PJRT_Buffer_Destroy = BufferDestroy;
  api.PJRT_Buffer_Dimensions = BufferDimensions;
  api.PJRT_Buffer_ElementType = BufferElementType;
  api.PJRT_Buffer_ToHostBuffer = BufferToHostBuffer;
  api.PJRT_LoadedExecutable_Destroy = ExecutableDestroy;
  api.PJRT_LoadedExecutable_Execute = Execute;
  api.PJRT_LoadedExecutable_GetExecutable = GetExecutable;
  api.PJRT_Executable_NumOutputs = NumOutputs;
  return &api;
}
