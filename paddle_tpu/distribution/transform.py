"""Bijector library (reference: python/paddle/distribution/transform.py —
AbsTransform :342, ChainTransform :496, IndependentTransform :670,
PowerTransform :765, ReshapeTransform :829, SoftmaxTransform :995,
StackTransform :1051, StickBreakingTransform :1171, TanhTransform :1237;
Affine/Exp/Sigmoid live in distributions.py).

All forward/inverse/log-det maps are jnp compositions running through
`apply`, so they are jittable and differentiable."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..autograd.function import apply
from ..core.tensor import as_tensor
from .distributions import (AffineTransform, ExpTransform,  # noqa: F401
                            SigmoidTransform, Transform)

__all__ = ["Transform", "AbsTransform", "AffineTransform", "ChainTransform",
           "ExpTransform", "IndependentTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform"]


class AbsTransform(Transform):
    """y = |x| (reference transform.py:342). Not injective: inverse maps
    to the positive branch."""

    def forward(self, x):
        return apply(jnp.abs, as_tensor(x), name="abs_fwd")

    def inverse(self, y):
        return apply(lambda a: a, as_tensor(y), name="abs_inv")

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            "AbsTransform is not bijective; log|det J| undefined")


class ChainTransform(Transform):
    """Composition t_n ∘ ... ∘ t_1 (reference transform.py:496)."""

    def __init__(self, transforms):
        if not isinstance(transforms, (list, tuple)):
            raise TypeError("transforms must be a list/tuple of Transform")
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ld = t.forward_log_det_jacobian(x)
            total = ld if total is None else total + ld
            x = t.forward(x)
        return total


class IndependentTransform(Transform):
    """Reinterpret trailing batch dims as event dims (reference
    transform.py:670): log-det sums over the reinterpreted dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self._base.forward(x)

    def inverse(self, y):
        return self._base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ld = self._base.forward_log_det_jacobian(x)
        return apply(
            lambda a: jnp.sum(a, axis=tuple(range(-self._rank, 0))), ld,
            name="independent_logdet")


class PowerTransform(Transform):
    """y = x^p (reference transform.py:765)."""

    def __init__(self, power):
        self.power = as_tensor(power)

    def forward(self, x):
        return apply(lambda a, p: jnp.power(a, p), as_tensor(x), self.power,
                     name="power_fwd")

    def inverse(self, y):
        return apply(lambda a, p: jnp.power(a, 1.0 / p), as_tensor(y),
                     self.power, name="power_inv")

    def forward_log_det_jacobian(self, x):
        return apply(
            lambda a, p: jnp.log(jnp.abs(p * jnp.power(a, p - 1.0))),
            as_tensor(x), self.power, name="power_logdet")


class ReshapeTransform(Transform):
    """Reshape the event block (reference transform.py:829)."""

    def __init__(self, in_event_shape, out_event_shape):
        self._in = tuple(int(s) for s in in_event_shape)
        self._out = tuple(int(s) for s in out_event_shape)
        if math.prod(self._in) != math.prod(self._out):
            raise ValueError(
                f"in_event_shape {self._in} and out_event_shape {self._out} "
                "must have the same number of elements")

    @property
    def in_event_shape(self):
        return self._in

    @property
    def out_event_shape(self):
        return self._out

    def forward(self, x):
        def f(a):
            batch = a.shape[:a.ndim - len(self._in)]
            return a.reshape(batch + self._out)
        return apply(f, as_tensor(x), name="reshape_fwd")

    def inverse(self, y):
        def f(a):
            batch = a.shape[:a.ndim - len(self._out)]
            return a.reshape(batch + self._in)
        return apply(f, as_tensor(y), name="reshape_inv")

    def forward_log_det_jacobian(self, x):
        def f(a):
            batch = a.shape[:a.ndim - len(self._in)]
            return jnp.zeros(batch, a.dtype)
        return apply(f, as_tensor(x), name="reshape_logdet")


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last dim (reference transform.py:995).
    Not bijective: inverse is the log map."""

    def forward(self, x):
        return apply(lambda a: jax.nn.softmax(a, axis=-1), as_tensor(x),
                     name="softmax_fwd")

    def inverse(self, y):
        return apply(jnp.log, as_tensor(y), name="softmax_inv")

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not bijective; log|det J| undefined")


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis` (reference
    transform.py:1051)."""

    def __init__(self, transforms, axis=0):
        if not isinstance(transforms, (list, tuple)) or not transforms:
            raise TypeError("transforms must be a non-empty list/tuple")
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, value, method):
        value = as_tensor(value)
        n = len(self.transforms)
        if int(value.shape[self.axis]) != n:
            raise ValueError(
                f"axis {self.axis} of the input (size "
                f"{value.shape[self.axis]}) must equal the number of "
                f"transforms ({n})")
        from .. import stack as _  # noqa: F401  (ensure package init)
        import paddle_tpu as paddle
        slices = paddle.unstack(value, axis=self.axis)
        outs = [getattr(t, method)(s)
                for t, s in zip(self.transforms, slices)]
        return paddle.stack(outs, axis=self.axis)

    def forward(self, x):
        return self._map(x, "forward")

    def inverse(self, y):
        return self._map(y, "inverse")

    def forward_log_det_jacobian(self, x):
        return self._map(x, "forward_log_det_jacobian")


class StickBreakingTransform(Transform):
    """Unconstrained R^K -> (K+1)-simplex by stick breaking (reference
    transform.py:1171; formulas match _forward/_inverse/_fldj there)."""

    def forward(self, x):
        def f(a):
            k = a.shape[-1]
            offset = k + 1 - jnp.cumsum(jnp.ones((k,), a.dtype), -1)
            z = jax.nn.sigmoid(a - jnp.log(offset))
            z_cumprod = jnp.cumprod(1 - z, -1)
            pad_z = jnp.concatenate(
                [z, jnp.ones(a.shape[:-1] + (1,), a.dtype)], -1)
            pad_cp = jnp.concatenate(
                [jnp.ones(a.shape[:-1] + (1,), a.dtype), z_cumprod], -1)
            return pad_z * pad_cp
        return apply(f, as_tensor(x), name="stickbreaking_fwd")

    def inverse(self, y):
        def f(a):
            y_crop = a[..., :-1]
            k = y_crop.shape[-1]
            offset = a.shape[-1] - jnp.cumsum(jnp.ones((k,), a.dtype), -1)
            sf = 1 - jnp.cumsum(y_crop, -1)
            return jnp.log(y_crop) - jnp.log(sf) + jnp.log(offset)
        return apply(f, as_tensor(y), name="stickbreaking_inv")

    def forward_log_det_jacobian(self, x):
        def f(a):
            k = a.shape[-1]
            offset = k + 1 - jnp.cumsum(jnp.ones((k,), a.dtype), -1)
            z = jax.nn.sigmoid(a - jnp.log(offset))
            z_cumprod = jnp.cumprod(1 - z, -1)
            y = jnp.concatenate(
                [z, jnp.ones(a.shape[:-1] + (1,), a.dtype)], -1) * \
                jnp.concatenate(
                    [jnp.ones(a.shape[:-1] + (1,), a.dtype), z_cumprod], -1)
            xs = a - jnp.log(offset)
            return jnp.sum(-xs + jax.nn.log_sigmoid(xs)
                           + jnp.log(y[..., :-1]), -1)
        return apply(f, as_tensor(x), name="stickbreaking_logdet")


class TanhTransform(Transform):
    """y = tanh(x) (reference transform.py:1237); log|det J| uses the
    numerically-stable 2(log2 - x - softplus(-2x)) form."""

    def forward(self, x):
        return apply(jnp.tanh, as_tensor(x), name="tanh_fwd")

    def inverse(self, y):
        return apply(jnp.arctanh, as_tensor(y), name="tanh_inv")

    def forward_log_det_jacobian(self, x):
        return apply(
            lambda a: 2.0 * (jnp.log(2.0) - a - jax.nn.softplus(-2.0 * a)),
            as_tensor(x), name="tanh_logdet")
