"""Distributions (reference: python/paddle/distribution/{normal,uniform,
categorical,bernoulli,exponential,beta,gumbel,laplace,kl}.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..autograd.function import apply
from ..core import generator as gen_mod
from ..core.tensor import Tensor, as_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Beta", "Gumbel", "Laplace", "kl_divergence",
           "register_kl"]


def _arr(x):
    return as_tensor(x)._data if not isinstance(x, (int, float)) \
        else jnp.float32(x)


def _key():
    return gen_mod.default_generator.split()


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply(lambda lp: jnp.exp(lp), self.log_prob(value),
                     name="prob")

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Differentiable: loc/scale given as Tensors keep their autograd
    linkage — log_prob and rsample route through `apply`, so REINFORCE and
    reparameterized-gradient training both work."""

    def __init__(self, loc, scale, name=None):
        self._loc_t = as_tensor(loc)
        self._scale_t = as_tensor(scale)

    @property
    def loc(self):
        return self._loc_t._data

    @property
    def scale(self):
        return self._scale_t._data

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc,
                                       jnp.shape(self.loc + self.scale)))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2,
                                       jnp.shape(self.loc + self.scale)))

    def _shape(self, shape):
        base = jnp.shape(self.loc + self.scale)
        return tuple(shape) + base

    def rsample(self, shape=()):
        eps = jax.random.normal(_key(), self._shape(shape))
        return apply(lambda m, s: m + s * eps, self._loc_t, self._scale_t,
                     name="normal_rsample")

    sample = rsample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x, m, s: -((x - m) ** 2) / (2 * s ** 2) - jnp.log(s)
            - 0.5 * math.log(2 * math.pi),
            v, self._loc_t, self._scale_t, name="normal_log_prob")

    def entropy(self):
        return apply(
            lambda m, s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                jnp.shape(m + s)),
            self._loc_t, self._scale_t, name="normal_entropy")


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=()):
        base = jnp.shape(self.low + self.high)
        u = jax.random.uniform(_key(), tuple(shape) + base)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x: jnp.where((x >= self.low) & (x <= self.high),
                                -jnp.log(self.high - self.low), -jnp.inf),
            v, name="uniform_log_prob")

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)

    def sample(self, shape=()):
        batch = jnp.shape(self.logits)[:-1]
        out_shape = tuple(shape) + batch
        return Tensor(jax.random.categorical(_key(), self.logits,
                                             shape=out_shape or None))

    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, axis=-1))

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda i: jnp.take_along_axis(
                jax.nn.log_softmax(self.logits, -1),
                i[..., None].astype(jnp.int32), axis=-1)[..., 0],
            v, name="categorical_log_prob")

    def entropy(self):
        p = jax.nn.softmax(self.logits, -1)
        lp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(p * lp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _arr(probs)

    def sample(self, shape=()):
        base = jnp.shape(self.probs_)
        return Tensor(jax.random.bernoulli(
            _key(), self.probs_, tuple(shape) + base).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor(value)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return apply(lambda x: x * jnp.log(p) + (1 - x) * jnp.log(1 - p),
                     v, name="bernoulli_log_prob")

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)

    def sample(self, shape=()):
        base = jnp.shape(self.rate)
        u = jax.random.exponential(_key(), tuple(shape) + base)
        return Tensor(u / self.rate)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(lambda x: jnp.log(self.rate) - self.rate * x, v,
                     name="exponential_log_prob")

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)

    def sample(self, shape=()):
        base = jnp.shape(self.alpha + self.beta)
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta,
                                      tuple(shape) + base))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = as_tensor(value)
        a, b = self.alpha, self.beta
        return apply(
            lambda x: (a - 1) * jnp.log(x) + (b - 1) * jnp.log1p(-x)
            - betaln(a, b), v, name="beta_log_prob")

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=()):
        base = jnp.shape(self.loc + self.scale)
        g = jax.random.gumbel(_key(), tuple(shape) + base)
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)

        def f(x):
            z = (x - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return apply(f, v, name="gumbel_log_prob")


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=()):
        base = jnp.shape(self.loc + self.scale)
        l = jax.random.laplace(_key(), tuple(shape) + base)
        return Tensor(self.loc + self.scale * l)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x: -jnp.abs(x - self.loc) / self.scale
            - jnp.log(2 * self.scale), v, name="laplace_log_prob")

    def entropy(self):
        return Tensor(1.0 + jnp.log(2 * self.scale))


# -- KL registry (reference: distribution/kl.py) -----------------------------

_KL_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    pp = jax.nn.softmax(p.logits, -1)
    return Tensor(jnp.sum(
        pp * (jax.nn.log_softmax(p.logits, -1)
              - jax.nn.log_softmax(q.logits, -1)), axis=-1))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(1 / r) + r - 1)
