"""Distributions (reference: python/paddle/distribution/{normal,uniform,
categorical,bernoulli,exponential,beta,gumbel,laplace,kl}.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..autograd.function import apply
from ..core import generator as gen_mod
from ..core.tensor import Tensor, as_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Beta", "Gumbel", "Laplace", "kl_divergence",
           "register_kl"]


def _arr(x):
    return as_tensor(x)._data if not isinstance(x, (int, float)) \
        else jnp.float32(x)


def _key():
    return gen_mod.default_generator.split()


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply(lambda lp: jnp.exp(lp), self.log_prob(value),
                     name="prob")

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Differentiable: loc/scale given as Tensors keep their autograd
    linkage — log_prob and rsample route through `apply`, so REINFORCE and
    reparameterized-gradient training both work."""

    def __init__(self, loc, scale, name=None):
        self._loc_t = as_tensor(loc)
        self._scale_t = as_tensor(scale)

    @property
    def loc(self):
        return self._loc_t._data

    @property
    def scale(self):
        return self._scale_t._data

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc,
                                       jnp.shape(self.loc + self.scale)))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2,
                                       jnp.shape(self.loc + self.scale)))

    def _shape(self, shape):
        base = jnp.shape(self.loc + self.scale)
        return tuple(shape) + base

    def rsample(self, shape=()):
        eps = jax.random.normal(_key(), self._shape(shape))
        return apply(lambda m, s: m + s * eps, self._loc_t, self._scale_t,
                     name="normal_rsample")

    sample = rsample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x, m, s: -((x - m) ** 2) / (2 * s ** 2) - jnp.log(s)
            - 0.5 * math.log(2 * math.pi),
            v, self._loc_t, self._scale_t, name="normal_log_prob")

    def entropy(self):
        return apply(
            lambda m, s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                jnp.shape(m + s)),
            self._loc_t, self._scale_t, name="normal_entropy")


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=()):
        base = jnp.shape(self.low + self.high)
        u = jax.random.uniform(_key(), tuple(shape) + base)
        return Tensor(self.low + (self.high - self.low) * u)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x: jnp.where((x >= self.low) & (x <= self.high),
                                -jnp.log(self.high - self.low), -jnp.inf),
            v, name="uniform_log_prob")

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)

    def sample(self, shape=()):
        batch = jnp.shape(self.logits)[:-1]
        out_shape = tuple(shape) + batch
        return Tensor(jax.random.categorical(_key(), self.logits,
                                             shape=out_shape or None))

    def probs(self):
        return Tensor(jax.nn.softmax(self.logits, axis=-1))

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda i: jnp.take_along_axis(
                jax.nn.log_softmax(self.logits, -1),
                i[..., None].astype(jnp.int32), axis=-1)[..., 0],
            v, name="categorical_log_prob")

    def entropy(self):
        p = jax.nn.softmax(self.logits, -1)
        lp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(p * lp, axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _arr(probs)

    def sample(self, shape=()):
        base = jnp.shape(self.probs_)
        return Tensor(jax.random.bernoulli(
            _key(), self.probs_, tuple(shape) + base).astype(jnp.float32))

    def log_prob(self, value):
        v = as_tensor(value)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return apply(lambda x: x * jnp.log(p) + (1 - x) * jnp.log(1 - p),
                     v, name="bernoulli_log_prob")

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log(1 - p)))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)

    def sample(self, shape=()):
        base = jnp.shape(self.rate)
        u = jax.random.exponential(_key(), tuple(shape) + base)
        return Tensor(u / self.rate)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(lambda x: jnp.log(self.rate) - self.rate * x, v,
                     name="exponential_log_prob")

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)

    def sample(self, shape=()):
        base = jnp.shape(self.alpha + self.beta)
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta,
                                      tuple(shape) + base))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = as_tensor(value)
        a, b = self.alpha, self.beta
        return apply(
            lambda x: (a - 1) * jnp.log(x) + (b - 1) * jnp.log1p(-x)
            - betaln(a, b), v, name="beta_log_prob")

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=()):
        base = jnp.shape(self.loc + self.scale)
        g = jax.random.gumbel(_key(), tuple(shape) + base)
        return Tensor(self.loc + self.scale * g)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)

        def f(x):
            z = (x - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return apply(f, v, name="gumbel_log_prob")


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=()):
        base = jnp.shape(self.loc + self.scale)
        l = jax.random.laplace(_key(), tuple(shape) + base)
        return Tensor(self.loc + self.scale * l)

    rsample = sample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x: -jnp.abs(x - self.loc) / self.scale
            - jnp.log(2 * self.scale), v, name="laplace_log_prob")

    def entropy(self):
        return Tensor(1.0 + jnp.log(2 * self.scale))


# -- KL registry (reference: distribution/kl.py) -----------------------------

_KL_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    pp = jax.nn.softmax(p.logits, -1)
    return Tensor(jnp.sum(
        pp * (jax.nn.log_softmax(p.logits, -1)
              - jax.nn.log_softmax(q.logits, -1)), axis=-1))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(1 / r) + r - 1)


class Cauchy(Distribution):
    """Reference distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self._loc_t = as_tensor(loc)
        self._scale_t = as_tensor(scale)

    @property
    def loc(self):
        return self._loc_t._data

    @property
    def scale(self):
        return self._scale_t._data

    def sample(self, shape=()):
        base = jnp.shape(self.loc + self.scale)
        u = jax.random.uniform(_key(), tuple(shape) + base,
                               minval=1e-6, maxval=1 - 1e-6)
        return apply(lambda m, s: m + s * jnp.tan(math.pi * (u - 0.5)),
                     self._loc_t, self._scale_t, name="cauchy_sample")

    rsample = sample

    def log_prob(self, value):
        return apply(
            lambda x, m, s: -jnp.log(math.pi * s * (1 + ((x - m) / s) ** 2)),
            as_tensor(value), self._loc_t, self._scale_t,
            name="cauchy_log_prob")

    def entropy(self):
        return apply(lambda m, s: jnp.broadcast_to(
            jnp.log(4 * math.pi * s), jnp.shape(m + s)),
            self._loc_t, self._scale_t, name="cauchy_entropy")


class Geometric(Distribution):
    """Reference distribution/geometric.py: trials until first success,
    support {0, 1, 2, ...}."""

    def __init__(self, probs, name=None):
        self._probs_t = as_tensor(probs)

    @property
    def probs(self):
        return self._probs_t._data

    @property
    def mean(self):
        return Tensor((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return Tensor((1 - self.probs) / self.probs ** 2)

    def sample(self, shape=()):
        u = jax.random.uniform(_key(),
                               tuple(shape) + jnp.shape(self.probs),
                               minval=1e-7, maxval=1 - 1e-7)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        return apply(lambda k, p: k * jnp.log1p(-p) + jnp.log(p),
                     as_tensor(value), self._probs_t,
                     name="geometric_log_prob")

    def entropy(self):
        p = self._probs_t
        return apply(
            lambda p: -((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p,
            p, name="geometric_entropy")


class LogNormal(Distribution):
    """Reference distribution/lognormal.py: exp of a Normal."""

    def __init__(self, loc, scale, name=None):
        self._base = Normal(loc, scale)

    @property
    def loc(self):
        return self._base.loc

    @property
    def scale(self):
        return self._base.scale

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + 0.5 * self._base.scale ** 2))

    def rsample(self, shape=()):
        return apply(lambda z: jnp.exp(z), self._base.rsample(shape),
                     name="lognormal_rsample")

    sample = rsample

    def log_prob(self, value):
        v = as_tensor(value)
        return apply(
            lambda x, m, s: -((jnp.log(x) - m) ** 2) / (2 * s ** 2)
            - jnp.log(s * x) - 0.5 * math.log(2 * math.pi),
            v, self._base._loc_t, self._base._scale_t,
            name="lognormal_log_prob")

    def entropy(self):
        return apply(
            lambda m, s: jnp.broadcast_to(
                m + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                jnp.shape(m + s)),
            self._base._loc_t, self._base._scale_t,
            name="lognormal_entropy")


class Dirichlet(Distribution):
    """Reference distribution/dirichlet.py."""

    def __init__(self, concentration, name=None):
        c = as_tensor(concentration)
        if not jnp.issubdtype(c._data.dtype, jnp.floating):
            # lax.lgamma/digamma and jax.random.dirichlet are float-strict
            c = apply(lambda a: a.astype(jnp.float32), c,
                      name="dirichlet_cast")
        self._conc_t = c

    @property
    def concentration(self):
        return self._conc_t._data

    @property
    def mean(self):
        c = self.concentration
        return Tensor(c / jnp.sum(c, -1, keepdims=True))

    def sample(self, shape=()):
        batch = jnp.shape(self.concentration)[:-1]
        return Tensor(jax.random.dirichlet(
            _key(), self.concentration, tuple(shape) + batch))

    def log_prob(self, value):
        def f(x, c):
            lognorm = jnp.sum(jax.lax.lgamma(c), -1) \
                - jax.lax.lgamma(jnp.sum(c, -1))
            return jnp.sum((c - 1) * jnp.log(x), -1) - lognorm
        return apply(f, as_tensor(value), self._conc_t,
                     name="dirichlet_log_prob")

    def entropy(self):
        def f(c):
            k = c.shape[-1]
            c0 = jnp.sum(c, -1)
            lognorm = jnp.sum(jax.lax.lgamma(c), -1) - jax.lax.lgamma(c0)
            return (lognorm + (c0 - k) * jax.lax.digamma(c0)
                    - jnp.sum((c - 1) * jax.lax.digamma(c), -1))
        return apply(f, self._conc_t, name="dirichlet_entropy")


class Multinomial(Distribution):
    """Reference distribution/multinomial.py: counts over k categories in
    `total_count` draws."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self._probs_t = as_tensor(probs)

    @property
    def probs(self):
        return self._probs_t._data

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    def sample(self, shape=()):
        # jax.random.multinomial draws counts in O(k) — materializing a
        # one-hot over total_count draws would scale memory with n
        batch = jnp.shape(self.probs)[:-1]
        out = jax.random.multinomial(
            _key(), jnp.float32(self.total_count),
            jnp.broadcast_to(self.probs,
                             tuple(shape) + jnp.shape(self.probs)))
        return Tensor(out)

    def log_prob(self, value):
        def f(x, p):
            x = x.astype(p.dtype)   # counts arrive as ints; lgamma is float
            logc = (jax.lax.lgamma(jnp.float32(self.total_count + 1))
                    - jnp.sum(jax.lax.lgamma(x + 1), -1))
            return logc + jnp.sum(x * jnp.log(jnp.maximum(p, 1e-30)), -1)
        return apply(f, as_tensor(value), self._probs_t,
                     name="multinomial_log_prob")


class Independent(Distribution):
    """Reference distribution/independent.py: reinterpret the rightmost
    `reinterpreted_batch_rank` batch dims as event dims (log_prob sums
    over them)."""

    def __init__(self, base, reinterpreted_batch_rank=1, name=None):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return apply(
            lambda a: jnp.sum(a, axis=tuple(range(-self.rank, 0))), lp,
            name="independent_log_prob")

    def entropy(self):
        e = self.base.entropy()
        return apply(
            lambda a: jnp.sum(a, axis=tuple(range(-self.rank, 0))), e,
            name="independent_entropy")


class Transform:
    """Reference distribution/transform.py base: forward/inverse +
    log|det J|."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc)
        self.scale = as_tensor(scale)

    def forward(self, x):
        return apply(lambda x, m, s: m + s * x, as_tensor(x), self.loc,
                     self.scale, name="affine_fwd")

    def inverse(self, y):
        return apply(lambda y, m, s: (y - m) / s, as_tensor(y), self.loc,
                     self.scale, name="affine_inv")

    def forward_log_det_jacobian(self, x):
        return apply(lambda x, s: jnp.broadcast_to(
            jnp.log(jnp.abs(s)), jnp.shape(x * s)), as_tensor(x),
            self.scale, name="affine_logdet")


class ExpTransform(Transform):
    def forward(self, x):
        return apply(lambda a: jnp.exp(a), as_tensor(x), name="exp_fwd")

    def inverse(self, y):
        return apply(lambda a: jnp.log(a), as_tensor(y), name="exp_inv")

    def forward_log_det_jacobian(self, x):
        return apply(lambda a: a, as_tensor(x), name="exp_logdet")


class SigmoidTransform(Transform):
    def forward(self, x):
        return apply(jax.nn.sigmoid, as_tensor(x), name="sigmoid_fwd")

    def inverse(self, y):
        return apply(lambda a: jnp.log(a) - jnp.log1p(-a), as_tensor(y),
                     name="sigmoid_inv")

    def forward_log_det_jacobian(self, x):
        return apply(
            lambda a: -jax.nn.softplus(-a) - jax.nn.softplus(a),
            as_tensor(x), name="sigmoid_logdet")


class TransformedDistribution(Distribution):
    """Reference distribution/transformed_distribution.py: push a base
    distribution through a chain of bijectors; log_prob uses the
    change-of-variables formula."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = list(transforms)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = as_tensor(value)
        lp = None
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ld = t.forward_log_det_jacobian(x)
            lp = ld if lp is None else apply(
                lambda a, b: a + b, lp, ld, name="td_logdet_acc")
            y = x
        base_lp = self.base.log_prob(y)
        if lp is None:   # empty transform chain: just the base
            return base_lp
        return apply(lambda a, b: a - b, base_lp, lp, name="td_log_prob")


__all__ += ["Cauchy", "Geometric", "LogNormal", "Dirichlet", "Multinomial",
            "Independent", "Transform", "AffineTransform", "ExpTransform",
            "SigmoidTransform", "TransformedDistribution"]


@register_kl(Geometric, Geometric)
def _kl_geo_geo(p, q):
    pp, qq = p.probs, q.probs
    return Tensor(jnp.log(pp / qq)
                  + (1 - pp) / pp * jnp.log((1 - pp) / (1 - qq)))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class ExponentialFamily(Distribution):
    """Base class for exponential-family distributions (reference:
    distribution/exponential_family.py:20): subclasses expose natural
    parameters and the log normalizer F; entropy falls out of the Bregman
    identity H = F(θ) - <θ, ∇F(θ)> + E[k(x)], with ∇F taken by jax
    autodiff (the reference differentiates the static graph the same way).
    """

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        nat = [jnp.asarray(getattr(p, "_data", p), jnp.float32)
               for p in self._natural_parameters]

        # grad of the SUMMED log normalizer gives the per-element ∇F for a
        # batch of independent distributions, so entropy keeps the batch
        # shape (the reference returns per-distribution entropies)
        grads = jax.grad(
            lambda *p: jnp.sum(self._log_normalizer(*p)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = self._log_normalizer(*nat) - sum(
            t * g for t, g in zip(nat, grads))
        return Tensor(ent - self._mean_carrier_measure)
