"""paddle.distribution equivalent (reference: python/paddle/distribution/).

Distributions are thin stateless wrappers over jnp math; sampling draws keys
from the framework generator so paddle.seed governs reproducibility.
"""

from .distributions import (  # noqa: F401
    Distribution, Normal, Uniform, Categorical, Bernoulli, Exponential,
    Beta, Gumbel, Laplace, kl_divergence, register_kl)

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Beta", "Gumbel", "Laplace", "kl_divergence",
           "register_kl"]
