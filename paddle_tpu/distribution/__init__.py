"""paddle.distribution equivalent (reference: python/paddle/distribution/).

Distributions are thin stateless wrappers over jnp math; sampling draws keys
from the framework generator so paddle.seed governs reproducibility.
"""

from .distributions import (  # noqa: F401
    AffineTransform, Bernoulli, Beta, Categorical, Cauchy, Dirichlet,
    Distribution, Exponential, ExpTransform, Geometric, Gumbel, Independent,
    Laplace, LogNormal, Multinomial, Normal, SigmoidTransform, Transform,
    TransformedDistribution, Uniform, kl_divergence, register_kl,
)
from .distributions import ExponentialFamily  # noqa: F401
from . import transform  # noqa: F401
from .transform import (  # noqa: F401
    AbsTransform, ChainTransform, IndependentTransform, PowerTransform,
    ReshapeTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform)

__all__ = ["ExponentialFamily", "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Beta", "Gumbel", "Laplace", "Cauchy", "Geometric",
           "LogNormal", "Dirichlet", "Multinomial", "Independent",
           "Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "TransformedDistribution", "kl_divergence",
           "AbsTransform", "ChainTransform", "IndependentTransform",
           "PowerTransform", "ReshapeTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform",
           "register_kl"]
