"""Telemetry exporters: Prometheus text exposition, JSON snapshot, and the
merge hook for the profiler's Chrome-trace export.

All stdlib-only, like the registry. The Prometheus renderer emits exactly
one ``# HELP`` + ``# TYPE`` pair per metric, series sorted by label set, so
output is deterministic (golden-testable) and scrapable by any Prometheus-
compatible agent tailing a file or hitting a debug endpoint.
"""

from __future__ import annotations

from .metrics import Counter, Gauge, Histogram, Registry, get_registry

__all__ = ["render_prometheus", "snapshot", "merge_into_chrome_trace"]


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s) -> str:
    return str(s).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key: tuple, extra: tuple = ()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_esc_label(v)}"' for k, v in pairs) + "}"


def _fmt_value(v) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 2 ** 53:
        return str(int(f))
    return repr(f)


def render_prometheus(registry: Registry | None = None) -> str:
    """Prometheus text exposition (version 0.0.4) of every metric in the
    registry. Metrics with no samples still get their HELP/TYPE header so
    scrapers learn the full schema."""
    reg = registry or get_registry()
    lines: list[str] = []
    for m in reg.metrics():
        # help-less metrics get a bare "# HELP name" line: a trailing
        # space is a grammar violation under strict parsers
        help_txt = _esc_help(m.help)
        lines.append(f"# HELP {m.name} {help_txt}" if help_txt
                     else f"# HELP {m.name}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, (Counter, Gauge)):
            for key, v in m._items():
                lines.append(f"{m.name}{_fmt_labels(key)} {_fmt_value(v)}")
        elif isinstance(m, Histogram):
            for key, _ in m._items():
                agg = m.value(**dict(key))
                for le, c in agg["buckets"].items():
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(key, (('le', le),))} {c}")
                lines.append(
                    f"{m.name}_sum{_fmt_labels(key)} "
                    f"{_fmt_value(agg['sum'])}")
                lines.append(
                    f"{m.name}_count{_fmt_labels(key)} {agg['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot(registry: Registry | None = None) -> dict:
    """JSON-safe snapshot of every sampled metric (the ``dump()`` payload
    bench.py embeds into its JSON line)."""
    return (registry or get_registry()).snapshot()


def merge_into_chrome_trace(trace: dict,
                            registry: Registry | None = None) -> dict:
    """Attach the telemetry snapshot to a Chrome-trace export dict under a
    top-level ``"telemetry"`` key. The ``traceEvents`` list itself is left
    untouched, so existing trace consumers see identical events."""
    trace["telemetry"] = snapshot(registry)
    return trace
