"""Flight recorder: a black box for every training run.

A bounded, thread-safe ring buffer of structured events that the
framework's instrumented layers (jit trace cache, collectives, profiler
spans, checkpoint/sentinel/preemption, fault injection, loader workers)
feed through a single :func:`record` call. When a run dies — NaN rewind
exhaustion, SIGTERM/SIGINT, an unhandled exception — the recorder dumps a
self-contained ``flight_<step>.json`` next to the checkpoint directory so
the events leading up to death survive the process.

Design constraints (mirrors ``metrics.py``):

* stdlib-only at import time — every hot layer imports this module; jax
  and the exporters are pulled in lazily, only inside :func:`dump`.
* recording one event costs ~one dict build + one deque append. There is
  NO lock on the hot path: ``deque.append`` (bounded by ``maxlen``) and
  ``itertools.count`` are both atomic under the GIL **and safe from a
  signal handler** — the preemption handler records from async-signal
  context, where a held non-reentrant lock would deadlock.
* disabled (``PADDLE_TPU_FLIGHT=0`` or ``enable(False)``) means
  :func:`record` returns after one attribute load + bool test; hot call
  sites additionally guard with ``if flight.enabled():`` so not even the
  kwargs dict is allocated.

Event schema: every event is a flat JSON-safe dict
``{"seq": int, "t": epoch-seconds, "kind": str, **fields}``. Well-known
kinds (see docs/observability.md for the field tables): ``step``,
``span_open``/``span_close``, ``jit_trace`` (with ``retrace`` flag),
``jit_compile``, ``collective``, ``checkpoint_save``,
``checkpoint_restore``, ``nan_window``/``nan_skip``/``nan_rewind``/
``nan_raise``, ``preempt``/``preempt_exit``, ``fault_injected``,
``worker_dead``, ``exception``.

CLI: ``python -m paddle_tpu.observability.flight <dump.json>`` renders
the timeline, top memory owners and the final events before death;
``--chrome-trace out.json`` converts the event tape to a Chrome trace.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from collections import deque

__all__ = [
    "FlightRecorder", "DEFAULT_CAPACITY", "SCHEMA_VERSION",
    "get_recorder", "record", "events", "clear", "enabled", "enable",
    "set_dump_dir", "get_dump_dir", "dump", "last_dump_path",
    "install_excepthook", "uninstall_excepthook",
    "load_dump", "render", "to_chrome_trace", "main",
]

DEFAULT_CAPACITY = 4096
SCHEMA_VERSION = 1


def _env_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_FLIGHT", "1").lower() not in (
        "0", "false", "off")


def _env_capacity() -> int:
    try:
        return max(int(os.environ.get("PADDLE_TPU_FLIGHT_EVENTS",
                                      DEFAULT_CAPACITY)), 16)
    except ValueError:
        return DEFAULT_CAPACITY


class FlightRecorder:
    """Bounded event tape. ``capacity`` is the ring size (oldest events
    fall off); ``enabled`` gates recording, not dumping."""

    def __init__(self, capacity: int | None = None,
                 enabled: bool | None = None):
        self.capacity = _env_capacity() if capacity is None else int(capacity)
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = itertools.count()
        self.dump_dir: str | None = None
        self.last_dump_path: str | None = None
        # dumping IS locked: it's cold, and two death paths racing (e.g.
        # excepthook + preemption drain) must not interleave file writes
        self._dump_lock = threading.Lock()

    # -- hot path ------------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one structured event. ~one dict + one atomic append when
        enabled; a single attribute test when disabled. Signal-safe."""
        if not self.enabled:
            return
        fields["seq"] = next(self._seq)
        fields["t"] = time.time()
        fields["kind"] = kind
        self._events.append(fields)

    # -- reads ---------------------------------------------------------------

    def events(self, last: int | None = None) -> list:
        """Snapshot of the tape, oldest first (``last`` trims to the most
        recent N; 0 means none). list(deque) is atomic under the GIL."""
        snap = list(self._events)
        if last is None:
            return snap
        return snap[-last:] if last > 0 else []

    def clear(self) -> None:
        self._events.clear()

    def __len__(self) -> int:
        return len(self._events)

    # -- dump ----------------------------------------------------------------

    def dump(self, reason: str, step: int | None = None,
             path: str | None = None, extra: dict | None = None,
             last: int | None = None,
             dump_dir: str | None = None) -> str | None:
        """Write a self-contained forensic dump and return its path.

        Contents: schema/reason/step/time, the event tape (last-N), the
        metrics snapshot (``observability.exporters.snapshot``), a memory
        census + the latest per-module attribution, and an env/config
        fingerprint. ``dump_dir`` overrides the recorder-wide directory
        for this one dump (the resilience paths pass their own manager's
        root, so a multi-manager process never routes a training dump to
        an eval checkpoint dir). Returns None when the recorder is
        disabled (no forensics were requested) or the write itself fails —
        a dying process must never die *again* in its black box."""
        if not self.enabled:
            return None
        with self._dump_lock:
            try:
                payload = self._payload(reason, step, extra, last)
                if path is None:
                    d = self._dir(dump_dir)
                    stem = f"flight_{int(step)}" if step is not None \
                        else "flight_final"
                    path = os.path.join(d, f"{stem}.json")
                    n = 2
                    while os.path.exists(path):
                        # never clobber an earlier black box at the same
                        # step (async save-error + sentinel rewind can both
                        # dump for one step; each is distinct forensics)
                        path = os.path.join(d, f"{stem}-{n}.json")
                        n += 1
                tmp = f"{path}.tmp-{os.getpid()}"
                with open(tmp, "w") as f:
                    # sanitize first: a NaN loss on the tape is the FLAGSHIP
                    # case, and bare NaN tokens are not RFC-8259 JSON (jq,
                    # JSON.parse and Perfetto all reject them)
                    json.dump(_finite(payload), f, default=_json_safe)
                os.replace(tmp, path)
                self.last_dump_path = path
                return path
            except Exception:
                return None

    def _dir(self, override: str | None = None) -> str:
        d = override or self.dump_dir or \
            os.environ.get("PADDLE_TPU_FLIGHT_DIR") or "."
        os.makedirs(d, exist_ok=True)
        return d

    def _payload(self, reason, step, extra, last) -> dict:
        payload = {
            "schema": SCHEMA_VERSION,
            "reason": reason,
            "step": step,
            "time": time.time(),
            "events": self.events(last),
            "fingerprint": _fingerprint(),
        }
        try:  # lazy: exporters is stdlib-only but keep dump failure-proof
            from ..exporters import snapshot
            payload["metrics"] = snapshot()
        except Exception:
            payload["metrics"] = None
        try:  # lazy: memory census may touch jax
            from .. import memory as _memory
            payload["memory"] = _memory.census()
            payload["module_peaks"] = _memory.last_attribution()
        except Exception:
            payload["memory"] = None
            payload["module_peaks"] = None
        try:
            # continuous-profiler picture: measured per-program shares +
            # the LAST reconciled fusion-target table (never re-analyzed
            # here — a dying process must not start tracing jaxprs)
            from .. import continuous as _continuous
            payload["profile"] = _continuous.profile_snapshot()
        except Exception:
            payload["profile"] = None
        # request-tracer picture: open spans of in-flight requests + the
        # request-log tail — only if the tracer is actually loaded (a
        # dying process must never import new modules from the dump path)
        tracing_mod = sys.modules.get("paddle_tpu.observability.tracing")
        if tracing_mod is not None:
            try:
                payload["tracing"] = tracing_mod.flight_snapshot()
            except Exception:
                payload["tracing"] = None
        # input-pipeline cursors: where every live checkpointable loader's
        # stream died (epoch/cursor/in-flight) — same no-new-imports rule
        io_state_mod = sys.modules.get("paddle_tpu.io.state")
        if io_state_mod is not None:
            try:
                snap = io_state_mod.snapshot_active()
                if snap:
                    payload["iterator_state"] = snap
            except Exception:
                payload["iterator_state"] = None
        # training-health picture: the monitor's last window statistics
        # and anomaly tallies (what the run's dynamics looked like on the
        # way down) — same no-new-imports rule
        health_mod = sys.modules.get("paddle_tpu.observability.health")
        if health_mod is not None:
            try:
                snap = health_mod.snapshot_for_flight()
                if snap:
                    payload["health"] = snap
            except Exception:
                payload["health"] = None
        if extra:
            payload["extra"] = extra
        return payload


def _json_safe(o):
    try:
        f = float(o)
        return f if f == f and f not in (float("inf"), float("-inf")) \
            else repr(f)
    except Exception:
        return repr(o)


def _finite(o):
    """Recursively replace non-finite floats with their repr strings so the
    dump is strict RFC-8259 JSON (json.dump would otherwise emit bare
    ``NaN``/``Infinity`` tokens)."""
    if isinstance(o, float):
        if o != o or o in (float("inf"), float("-inf")):
            return repr(o)
        return o
    if isinstance(o, dict):
        return {k: _finite(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_finite(v) for v in o]
    return o


def _fingerprint() -> dict:
    """Env/config fingerprint: enough to answer "what exactly was this
    process" from the dump alone, small enough to always include."""
    import platform
    keep = {}
    for k in sorted(os.environ):
        if k.startswith(("PADDLE_TPU_", "JAX_", "XLA_", "PALLAS_")):
            keep[k] = os.environ[k]
    out = {
        "argv": list(sys.argv),
        "pid": os.getpid(),
        "cwd": os.getcwd(),
        "python": platform.python_version(),
        "platform": platform.platform(),
        "env": keep,
    }
    # active parallelism plan (post-mortems must name the topology the
    # process died under) — only if the planner is actually loaded: a
    # dying process must never import new modules from the dump path
    plan_mod = sys.modules.get("paddle_tpu.planner.plan")
    if plan_mod is not None:
        try:
            active = plan_mod.active_plan()
        except Exception:
            active = None
        if active:
            out["plan"] = dict(active)
    return out


# ---------------------------------------------------------------------------
# process-wide default recorder + module-level API
# ---------------------------------------------------------------------------

_default = FlightRecorder()


def get_recorder() -> FlightRecorder:
    """The process-wide recorder all framework instrumentation feeds."""
    return _default


def record(kind: str, **fields) -> None:
    _default.record(kind, **fields)


def events(last: int | None = None) -> list:
    return _default.events(last)


def clear() -> None:
    _default.clear()


def enabled() -> bool:
    """True while the recorder collects events (``PADDLE_TPU_FLIGHT`` env,
    overridable via :func:`enable`). Hot call sites guard on this so a
    disabled recorder costs nothing — not even the kwargs dict."""
    return _default.enabled


def enable(flag: bool = True) -> bool:
    _default.enabled = bool(flag)
    return _default.enabled


def set_dump_dir(path: str) -> None:
    """Where abnormal-death dumps land (CheckpointManager points this at
    its root, so the black box sits next to the checkpoints)."""
    _default.dump_dir = os.fspath(path)


def get_dump_dir() -> str | None:
    return _default.dump_dir


def dump(reason: str, step: int | None = None, path: str | None = None,
         extra: dict | None = None, last: int | None = None,
         dump_dir: str | None = None) -> str | None:
    return _default.dump(reason, step=step, path=path, extra=extra,
                         last=last, dump_dir=dump_dir)


def last_dump_path() -> str | None:
    return _default.last_dump_path


# ---------------------------------------------------------------------------
# unhandled-exception hook (chained, idempotent)
# ---------------------------------------------------------------------------

_prev_excepthook = None
_active_hook = None
_hook_running = False


def install_excepthook() -> None:
    """Chain a dump-on-unhandled-exception hook into ``sys.excepthook``.

    Idempotent in the strong sense: a no-op while our hook IS the current
    ``sys.excepthook``, and a **re-chain** when someone replaced the hook
    after a previous install (before this, a stale install marker made
    later installs silent no-ops that bypassed the replacement — the
    cross-test flip PR 6's tier-1 notes). The hook in front always runs
    afterwards, so tracebacks print exactly as before; if several flight
    hooks end up chained, a reentrancy guard makes only the outermost one
    dump. SystemExit/KeyboardInterrupt never reach excepthook, so normal
    exits and the preemption path (which dumps itself) are unaffected."""
    global _prev_excepthook, _active_hook
    if _active_hook is not None and sys.excepthook is _active_hook:
        return
    prev = sys.excepthook

    def _hook(etype, evalue, tb):
        global _hook_running
        outermost = not _hook_running
        _hook_running = True
        try:
            if outermost:
                try:
                    _default.record(
                        "exception",
                        type=getattr(etype, "__name__", str(etype)),
                        message=str(evalue)[:500])
                    _default.dump(reason="unhandled_exception")
                except Exception:
                    pass
            (prev or sys.__excepthook__)(etype, evalue, tb)
        finally:
            if outermost:
                _hook_running = False

    _prev_excepthook = prev
    _active_hook = _hook
    sys.excepthook = _hook


def uninstall_excepthook() -> None:
    """Undo :func:`install_excepthook` (test teardown uses this so one
    test's CheckpointManager cannot leave a chained hook that flips later
    excepthook tests). If something replaced ``sys.excepthook`` after our
    install, only the marker state is cleared — clobbering the
    replacement would be a different bug."""
    global _prev_excepthook, _active_hook
    if _active_hook is None:
        return
    if sys.excepthook is _active_hook:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
    _prev_excepthook = None
    _active_hook = None


# ---------------------------------------------------------------------------
# dump reader + renderers (the CLI side; cold path, imports numpy-free)
# ---------------------------------------------------------------------------

def load_dump(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _fmt_bytes(n) -> str:
    from ..memory import format_bytes
    return format_bytes(n)


def _fmt_event(e, t0) -> str:
    rest = {k: v for k, v in e.items() if k not in ("seq", "t", "kind")}
    body = " ".join(f"{k}={v}" for k, v in sorted(rest.items()))
    return f"  +{e.get('t', t0) - t0:10.3f}s  #{e.get('seq', '?'):>6}  " \
           f"{e.get('kind', '?'):<18} {body}"


def render(payload: dict, last: int = 25) -> str:
    """Human-readable view of a flight dump: header, top memory owners,
    per-module peaks, and the final events before death."""
    out = []
    evs = payload.get("events") or []
    t0 = evs[0]["t"] if evs else payload.get("time", 0.0)
    out.append("=" * 72)
    out.append(f"FLIGHT DUMP  reason={payload.get('reason')}  "
               f"step={payload.get('step')}  events={len(evs)}  "
               f"schema={payload.get('schema')}")
    fp = payload.get("fingerprint") or {}
    out.append(f"  argv: {' '.join(fp.get('argv', []))}")
    faults = (fp.get("env") or {}).get("PADDLE_TPU_FAULTS")
    if faults:
        out.append(f"  PADDLE_TPU_FAULTS: {faults}")
    out.append("=" * 72)

    mem = payload.get("memory") or {}
    dev = mem.get("device") or {}
    if dev:
        out.append("\n-- device memory " + "-" * 40)
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in dev:
                out.append(f"  {k:<20} {_fmt_bytes(dev[k])}")
    live = mem.get("live_arrays") or {}
    rows = live.get("by_dtype_shape") or []
    if rows:
        out.append("\n-- top live arrays (by dtype/shape) " + "-" * 24)
        out.append(f"  {'dtype':<10} {'shape':<24} {'count':>6} {'bytes':>12}")
        for r in rows[:12]:
            out.append(f"  {r.get('dtype', '?'):<10} "
                       f"{str(r.get('shape', '?')):<24} "
                       f"{r.get('count', 0):>6} "
                       f"{_fmt_bytes(r.get('bytes', 0)):>12}")
        out.append(f"  total: {_fmt_bytes(live.get('total_bytes', 0))} in "
                   f"{live.get('count', 0)} arrays")

    prof = payload.get("profile") or {}
    if prof.get("programs"):
        out.append("\n-- measured program shares (continuous profiler) "
                   + "-" * 10)
        out.append(f"  {'program':<36} {'ms/step':>9} {'share':>7} "
                   f"{'calls':>6}")
        rows = sorted(prof["programs"].items(),
                      key=lambda kv: -kv[1].get("ms_per_step", 0))
        for name, st in rows[:10]:
            out.append(f"  {name:<36} {st.get('ms_per_step', 0):>9.3f} "
                       f"{st.get('share', 0):>7.2%} "
                       f"{st.get('calls', 0):>6}")
        out.append(f"  sampler: every={prof.get('every')} steps, overhead "
                   f"{prof.get('overhead_pct', 0)}% "
                   f"(budget {prof.get('budget_pct')}%)")
    if prof.get("fusion_targets"):
        out.append("\n-- measured fusion targets (mega-kernel queue) "
                   + "-" * 13)
        for i, t in enumerate(prof["fusion_targets"][:5], 1):
            out.append(
                f"  {i}. {t.get('name', '?'):<24} x{t.get('sites', 1):<3} "
                f"{t.get('measured_ms_share', 0):>8.3f} ms/step  "
                f"{_fmt_bytes(t.get('est_saved_bytes', 0))} saved/site")

    peaks = payload.get("module_peaks") or {}
    if peaks:
        out.append("\n-- per-module peak HBM attribution " + "-" * 25)
        out.append(f"  {'module':<40} {'calls':>5} {'peak delta':>12} "
                   f"{'peak bytes':>12}")
        items = sorted(peaks.items(),
                       key=lambda kv: -kv[1].get("peak_delta_bytes", 0))
        for name, st in items[:20]:
            out.append(f"  {name:<40} {st.get('calls', 0):>5} "
                       f"{_fmt_bytes(st.get('peak_delta_bytes', 0)):>12} "
                       f"{_fmt_bytes(st.get('peak_bytes', 0)):>12}")

    if evs and last > 0:
        out.append(f"\n-- final {min(last, len(evs))} events before death "
                   + "-" * 30)
        for e in evs[-last:]:
            out.append(_fmt_event(e, t0))
    out.append("=" * 72)
    return "\n".join(out)


def to_chrome_trace(payload: dict) -> dict:
    """Chrome-trace (``chrome://tracing`` / Perfetto) conversion of the
    event tape: ``span_close`` events (which carry ``dur``) become complete
    ``ph="X"`` slices; everything else becomes an instant event. The
    metrics snapshot rides along under ``"telemetry"``, matching
    ``Profiler.export``'s merged form."""
    evs = payload.get("events") or []
    t0 = evs[0]["t"] if evs else 0.0
    pid = (payload.get("fingerprint") or {}).get("pid", 0)
    # pair span_open/span_close by name in tape order; opens the process
    # died inside (no matching close — the most interesting spans) must
    # still appear in the trace, as begin events
    open_stacks: dict = {}
    closed_opens = set()
    for e in evs:
        if e.get("kind") == "span_open":
            open_stacks.setdefault(e.get("name"), []).append(e.get("seq"))
        elif e.get("kind") == "span_close":
            stack = open_stacks.get(e.get("name"))
            if stack:
                closed_opens.add(stack.pop())
    trace_events = []
    for e in evs:
        ts_us = (e.get("t", t0) - t0) * 1e6
        name = e.get("name") or e.get("fn") or e.get("op") or \
            e.get("kind", "event")
        args = {k: v for k, v in e.items() if k not in ("t",)}
        if e.get("kind") == "span_close" and "dur" in e:
            dur_us = float(e["dur"]) * 1e6
            trace_events.append({"name": name, "ph": "X", "cat": "flight",
                                 "ts": ts_us - dur_us, "dur": dur_us,
                                 "pid": pid, "tid": 0, "args": args})
        elif e.get("kind") == "span_open":
            if e.get("seq") in closed_opens:
                continue  # its close slice already covers the interval
            trace_events.append({"name": name, "ph": "B", "cat": "flight",
                                 "ts": ts_us, "pid": pid, "tid": 0,
                                 "args": args})
        else:
            trace_events.append({"name": f"{e.get('kind')}:{name}", "ph": "i",
                                 "cat": "flight", "ts": ts_us, "pid": pid,
                                 "tid": 0, "s": "p", "args": args})
    out = {"traceEvents": trace_events,
           "flight": {k: payload.get(k) for k in
                      ("schema", "reason", "step", "time")}}
    if payload.get("metrics"):
        out["telemetry"] = payload["metrics"]
    return out


def main(argv=None) -> int:
    """``python -m paddle_tpu.observability.flight <dump.json>``"""
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.flight",
        description="Render a flight-recorder dump: timeline, top memory "
                    "owners, final events before death.")
    ap.add_argument("dump", help="path to a flight_<step>.json dump")
    ap.add_argument("--last", type=int, default=25,
                    help="how many trailing events to show (default 25)")
    ap.add_argument("--chrome-trace", metavar="OUT",
                    help="also write a Chrome-trace JSON conversion")
    ap.add_argument("--json", action="store_true",
                    help="print the raw payload instead of the rendering")
    args = ap.parse_args(argv)
    try:
        payload = load_dump(args.dump)
    except (OSError, ValueError) as e:
        print(f"cannot read flight dump {args.dump!r}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(payload, indent=2))
    else:
        print(render(payload, last=args.last))
    if args.chrome_trace:
        with open(args.chrome_trace, "w") as f:
            json.dump(to_chrome_trace(payload), f)
        print(f"\nchrome trace written to {args.chrome_trace} "
              f"(open in chrome://tracing or Perfetto)")
    return 0
