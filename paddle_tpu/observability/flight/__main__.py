"""``python -m paddle_tpu.observability.flight <dump.json>`` entry point
(a real ``__main__`` submodule so runpy never re-executes the already-
imported recorder module)."""

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
