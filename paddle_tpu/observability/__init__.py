"""paddle_tpu.observability — always-on runtime telemetry.

The metrics layer every perf PR reads from: a zero-dependency registry of
Counters, Gauges and fixed-bucket Histograms, plus exporters (Prometheus
text exposition, JSON snapshot, Chrome-trace merge). The framework's hot
layers are instrumented out of the box:

* ``jit.to_static`` — trace-cache hits/misses/retraces, trace seconds,
  per-function cache size (``paddle_tpu_jit_*``): a recompile storm is a
  first-class metric, not a mystery slowdown.
* ``distributed.communication`` — per-collective call counts and payload
  bytes by group (``paddle_tpu_comm_*``).
* ``io.DataLoader`` — batch wait-time vs consumer compute-time histograms
  (``paddle_tpu_io_*``).
* ``profiler.RecordEvent`` — span counts that survive after a trace window
  closes (``paddle_tpu_profiler_events_total``).
* :class:`StepTimer` — step latency, tokens/sec, analytic-FLOPs MFU, and
  host<->device transfer bytes (``paddle_tpu_step_*``), sharing bench.py's
  MFU math.
* ``resilience`` — checkpoint saves/restores/fallbacks, NaN-sentinel
  windows and rewinds, preemption drains, fault-harness activity
  (``paddle_tpu_resilience_*``; scaler-skipped inf steps under
  ``paddle_tpu_amp_scaler_found_inf_total``): recovery is a first-class
  metric family, not log noise.

Beyond metrics, two forensic layers (this PR's black box):

* :mod:`.flight` — an always-on bounded ring buffer of structured events
  (steps, spans, retraces, collectives, checkpoints, NaN windows,
  preemptions, injected faults) fed by the same instrumented layers via
  ``flight.record(kind, **fields)``; on abnormal death it dumps a
  self-contained ``flight_<step>.json`` next to the checkpoint dir, and
  ``python -m paddle_tpu.observability.flight <dump>`` renders it.
  Disable with ``PADDLE_TPU_FLIGHT=0``.
* :mod:`.memory` — HBM census (``device.memory_stats()`` +
  ``jax.live_arrays()`` by dtype/shape, exported as
  ``paddle_tpu_hbm_bytes{kind=...}`` gauges) and per-``nn.Layer`` peak
  attribution via ``memory.attribute_memory(model)``.

And the live layer (:mod:`.continuous`):

* a bounded-overhead **sampling profiler** (``continuous.on_step(step)``
  once per training step) that captures per-dispatched-program wall time
  into ``paddle_tpu_program_step_ms`` histograms every
  ``PADDLE_TPU_PROF_EVERY`` steps, backs its cadence off past the
  ``PADDLE_TPU_PROF_BUDGET_PCT`` overhead budget, and reconciles the
  measurements with the static fusion candidates into the ranked
  ``fusion_targets`` mega-kernel work queue;
* a zero-dependency **telemetry HTTP server** — :func:`serve`\\ ``(port)``
  (``PADDLE_TPU_METRICS_PORT``) with ``/metrics``, ``/healthz``,
  ``/flight`` and ``/profile?steps=N`` endpoints.

Metric names follow ``paddle_tpu_<area>_<name>_<unit>``. Collection is on
by default; ``PADDLE_TPU_METRICS=0`` (or :func:`enable`\\ ``(False)``)
turns every recording call into a near-zero-cost no-op.

Quick use::

    import paddle_tpu.observability as obs
    obs.dump()        # JSON-safe snapshot of every sampled metric
    obs.serve_text()  # Prometheus text exposition

NOT to be confused with ``paddle_tpu.metric`` — that package scores model
predictions (Accuracy/Precision/Recall/Auc); this one watches the system
run.
"""

from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, DEFAULT_BUCKETS,
    get_registry, counter, gauge, histogram,
    enabled, enable, value, total, reset,
)
from .exporters import (  # noqa: F401
    render_prometheus, snapshot, merge_into_chrome_trace,
)
from .step_timer import (  # noqa: F401
    StepTimer, device_peak_flops, analytic_mfu, PEAK_FLOPS_TABLE,
)
from . import flight  # noqa: F401
from . import memory  # noqa: F401
from . import tracing  # noqa: F401
from . import health  # noqa: F401  (after flight: health records to the tape)
from . import continuous  # noqa: F401
from .continuous import serve, shutdown_server, TelemetryServer  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "DEFAULT_BUCKETS",
    "get_registry", "counter", "gauge", "histogram",
    "enabled", "enable", "value", "total", "reset",
    "render_prometheus", "snapshot", "merge_into_chrome_trace",
    "StepTimer", "device_peak_flops", "analytic_mfu", "PEAK_FLOPS_TABLE",
    "dump", "serve_text", "flight", "memory", "tracing", "health",
    "continuous", "serve", "shutdown_server", "TelemetryServer",
]


def dump(registry=None) -> dict:
    """JSON-safe snapshot of every sampled metric — the payload bench.py
    embeds as its ``"telemetry"`` block."""
    return snapshot(registry)


def serve_text(registry=None) -> str:
    """Prometheus text exposition of the registry (one ``# TYPE`` line per
    metric), ready to serve from a /metrics endpoint or write to a file."""
    return render_prometheus(registry)
