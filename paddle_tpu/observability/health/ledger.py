"""Append-only per-run step-series ledger + run-to-run comparison.

One compact, strictly RFC-8259 JSON line per health window (step, wall
clock, loss, lr, grad norm, tokens/s, peak HBM, retrace count, fired
anomaly rules), so every run leaves a durable trajectory that outlives
the process — the measured-history artifact ``perf_trend``/``compare``
diff. The file is bounded: past ``max_bytes`` it rotates by atomic
rename (``path`` -> ``path.1`` -> ... -> ``path.keep``, older dropped),
so a long run can never fill the disk.

Non-finite values never reach the file as bare tokens: records pass
through the flight recorder's sanitizers and ``json.dumps(...,
allow_nan=False)`` proves it — a NaN loss arrives as the string
``"nan"``, parseable by any strict JSON reader.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time

from ..flight import _finite, _json_safe
from ...analysis.concurrency import tsan as _tsan

__all__ = ["StepLedger", "read_ledger", "compare_ledgers", "SCHEMA",
           "COMPARE_METRICS"]

SCHEMA = "paddle_tpu.health.ledger/1"

#: (metric, direction, aggregation) — how `compare` judges each series.
#: "lower"/"higher" say which way is better; "band" metrics are training
#: dynamics (a shift is worth flagging but is not a perf regression).
COMPARE_METRICS = (
    ("tokens_per_s", "higher", "median"),
    ("step_ms", "lower", "median"),
    ("loss", "lower", "median"),
    ("peak_hbm_bytes", "lower", "max"),
    ("retraces", "lower", "last"),
    ("grad_norm", "band", "median"),
    ("update_ratio", "band", "median"),
)


class StepLedger:
    """Bounded append-only JSONL ledger, one record per health window."""

    def __init__(self, path: str, run_id=None,
                 max_bytes: int = 4 * 1024 * 1024, keep: int = 2):
        if os.path.isdir(path):
            path = os.path.join(path, "health_ledger.jsonl")
        self.path = path
        self.run_id = str(run_id) if run_id is not None \
            else f"{int(time.time())}-{os.getpid()}"
        self.max_bytes = int(max_bytes)
        self.keep = max(0, int(keep))
        self.rotations = 0
        self._lock = _tsan.lock("health.ledger")
        self._f = None

    # -- write path ----------------------------------------------------------

    def _open(self):
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        fresh = not os.path.exists(self.path) or \
            os.path.getsize(self.path) == 0
        self._f = open(self.path, "a", encoding="utf-8")
        if fresh:
            self._write({"schema": SCHEMA, "run_id": self.run_id,
                         "wall": time.time()})

    def _write(self, rec: dict):
        line = json.dumps(_finite(rec), default=_json_safe,
                          separators=(",", ":"), allow_nan=False)
        self._f.write(line + "\n")
        self._f.flush()

    def append(self, rec: dict) -> None:
        with self._lock:
            if self._f is None:
                self._open()
            self._write(rec)
            if self._f.tell() > self.max_bytes:
                self._rotate()

    def _rotate(self):
        self._f.close()
        self._f = None
        if self.keep == 0:
            os.remove(self.path)
        else:
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        self.rotations += 1
        self._open()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- read / compare ----------------------------------------------------------

def read_ledger(path: str):
    """Parse one ledger file -> (header dict | None, list of row dicts)."""
    header, rows = None, []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "schema" in rec:
                if header is None:
                    header = rec
            else:
                rows.append(rec)
    return header, rows


def _num(v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(v) else None


def _agg(rows, key, how):
    vals = [x for x in (_num(r.get(key)) for r in rows) if x is not None]
    if not vals:
        return None
    if how == "last":
        return vals[-1]
    if how == "max":
        return max(vals)
    # steady half: skip the warmup/ramp windows at the head of the run
    return statistics.median(vals[len(vals) // 2:])


def compare_ledgers(base_rows, cur_rows, tol_pct: float = 5.0,
                    tols: dict | None = None) -> list:
    """Per-metric tolerance verdicts of `cur_rows` against `base_rows`.

    Returns a list of ``{"metric", "baseline", "current", "delta_pct",
    "direction", "tol_pct", "verdict"}`` with verdict one of ``ok``,
    ``improved``, ``regressed`` (directional metrics) or ``shifted``
    (band metrics). Metrics missing on either side are skipped; a
    per-metric tolerance <= 0 disables that metric."""
    tols = tols or {}
    out = []
    for key, direction, how in COMPARE_METRICS:
        tol = float(tols.get(key, tol_pct))
        if tol <= 0:
            continue
        b, c = _agg(base_rows, key, how), _agg(cur_rows, key, how)
        if b is None or c is None:
            continue
        delta = (c - b) / max(abs(b), 1e-12) * 100.0
        verdict = "ok"
        if direction == "band":
            if abs(delta) > tol:
                verdict = "shifted"
        elif direction == "lower":
            if delta > tol:
                verdict = "regressed"
            elif delta < -tol:
                verdict = "improved"
        else:  # higher is better
            if delta < -tol:
                verdict = "regressed"
            elif delta > tol:
                verdict = "improved"
        out.append({"metric": key, "baseline": b, "current": c,
                    "delta_pct": round(delta, 2), "direction": direction,
                    "tol_pct": tol, "verdict": verdict})
    return out
