"""CLI for step-series ledgers.

    python -m paddle_tpu.observability.health compare runA.jsonl runB.jsonl
        [--tol-pct 5] [--tol metric=pct ...] [--json]
    python -m paddle_tpu.observability.health show run.jsonl [--last 20]

``compare`` prints a per-metric verdict table (baseline = runA) and
exits non-zero when any directional metric regressed past tolerance.
"""

from __future__ import annotations

import argparse
import json
import sys

from .ledger import compare_ledgers, read_ledger


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _parse_tols(pairs):
    tols = {}
    for p in pairs:
        if "=" not in p:
            raise SystemExit(f"--tol wants METRIC=PCT, got {p!r}")
        k, v = p.split("=", 1)
        tols[k.strip()] = float(v)
    return tols


def _cmd_show(a) -> int:
    header, rows = read_ledger(a.path)
    if header:
        print(f"ledger {a.path}  schema={header.get('schema')}  "
              f"run_id={header.get('run_id')}  windows={len(rows)}")
    cols = ("step", "loss", "grad_norm", "update_ratio", "step_ms",
            "tokens_per_s", "retraces", "anomalies")
    print("  ".join(f"{c:>14}" for c in cols))
    for r in rows[-a.last:]:
        print("  ".join(f"{_fmt(r.get(c)):>14}"[:14].rjust(14)
                        for c in cols))
    return 0


def _cmd_compare(a) -> int:
    _, base = read_ledger(a.base)
    _, cur = read_ledger(a.current)
    if not base or not cur:
        print(f"compare: empty ledger ({a.base}: {len(base)} rows, "
              f"{a.current}: {len(cur)} rows)", file=sys.stderr)
        return 2
    results = compare_ledgers(base, cur, a.tol_pct, _parse_tols(a.tol))
    if a.json:
        print(json.dumps(results, indent=2))
    else:
        print(f"{'metric':>16} {'baseline':>12} {'current':>12} "
              f"{'delta':>9}  verdict")
        for r in results:
            print(f"{r['metric']:>16} {_fmt(r['baseline']):>12} "
                  f"{_fmt(r['current']):>12} {r['delta_pct']:>+8.2f}%  "
                  f"{r['verdict']}")
    bad = [r for r in results if r["verdict"] == "regressed"]
    for r in bad:
        print(f"REGRESSED: {r['metric']} {_fmt(r['baseline'])} -> "
              f"{_fmt(r['current'])} ({r['delta_pct']:+.2f}%, tolerance "
              f"{r['tol_pct']:g}%)", file=sys.stderr)
    return 1 if bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.health",
        description="step-series ledger tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("compare", help="diff two run ledgers")
    c.add_argument("base")
    c.add_argument("current")
    c.add_argument("--tol-pct", type=float, default=5.0,
                   help="default per-metric tolerance (percent)")
    c.add_argument("--tol", action="append", default=[],
                   metavar="METRIC=PCT",
                   help="per-metric tolerance override; <=0 disables")
    c.add_argument("--json", action="store_true")
    s = sub.add_parser("show", help="render one ledger")
    s.add_argument("path")
    s.add_argument("--last", type=int, default=20)
    a = p.parse_args(argv)
    try:
        return _cmd_show(a) if a.cmd == "show" else _cmd_compare(a)
    except (OSError, ValueError) as e:
        print(f"{a.cmd}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
