"""paddle_tpu.observability.health — training-health telemetry.

The training-side counterpart to the serving tracing/profiling stack:
where :class:`~paddle_tpu.resilience.NaNSentinel` sees only a binary
``isfinite``, the :class:`HealthMonitor` watches the run's *dynamics* —
per-layer gradient norms, parameter norms, update-to-weight ratios, the
global gradient norm and the loss — and raises structured anomalies
(loss spike, gradient explosion/vanish, dead layer, update ratio out of
band) before divergence turns into NaN.

Cost model (the NaNSentinel window pattern, applied to statistics):

* ``observe_grads()`` — called inside the train step, after
  ``optimizer.step()`` and before ``clear_grad()`` — folds every
  statistic into ONE stacked device array. Under a ``to_static`` trace
  the fold is inlined into the step program (zero extra dispatches, zero
  retraces: the accumulator is ordinary lifted state, exactly like
  optimizer moments); eagerly it is a single jitted program compiled
  once per monitor.
* ``observe(loss)`` — callable anywhere the loss Tensor is live (also
  outside the jitted step, so harness-corrupted losses are seen) — one
  device-side add, no sync.
* ``check(step)`` — on the ``check_every`` cadence only — performs the
  window's ONE device→host pull, evaluates the anomaly rules, exports
  ``paddle_tpu_health_*`` metrics, records ``health_anomaly`` flight
  events, and appends one line to the optional step-series
  :class:`~paddle_tpu.observability.health.ledger.StepLedger`.

When ``ClipGradByGlobalNorm`` is active, the global gradient norm is the
one the (fused) optimizer step already computed — exposed via
``clip.last_global_norm`` — not a second device reduction.

Run-to-run comparison::

    python -m paddle_tpu.observability.health compare runA.jsonl runB.jsonl

Live view: the telemetry server serves ``/dashboard`` (zero-dependency
HTML with inline SVG sparklines over the monitor's window history and
the live ledger).
"""

from __future__ import annotations

import collections
import math
import time
import weakref

from ..metrics import counter as _counter, gauge as _gauge, total as _total
from .. import flight as _flight
from ...analysis.concurrency import tsan as _tsan
from .ledger import StepLedger, read_ledger, compare_ledgers

__all__ = ["HealthMonitor", "HealthAnomalyError", "StepLedger",
           "read_ledger", "compare_ledgers", "get_monitor",
           "snapshot_for_flight", "RULES"]

#: the anomaly rule vocabulary, in evaluation order
RULES = ("loss_spike", "grad_explosion", "grad_vanish", "dead_layer",
         "update_ratio_oob")

_M_WINDOWS = _counter("paddle_tpu_health_windows_total",
                      "health check windows completed")
_M_PULLS = _counter("paddle_tpu_health_host_pulls_total",
                    "device->host stat pulls (exactly one per window)")
_M_ANOM = _counter("paddle_tpu_health_anomalies_total",
                   "anomaly-rule firings, labeled by rule")
_M_GRAD = _gauge("paddle_tpu_health_grad_norm",
                 "global gradient norm, window RMS (clip-provided when "
                 "ClipGradByGlobalNorm is active)")
_M_PARAM = _gauge("paddle_tpu_health_param_norm",
                  "global parameter norm at window end")
_M_RATIO = _gauge("paddle_tpu_health_update_ratio",
                  "global update-to-weight proxy lr*|g|/|p|")
_M_LOSS = _gauge("paddle_tpu_health_loss", "window-mean loss")
_M_LAYER = _gauge("paddle_tpu_health_layer_grad_norm",
                  "per-parameter gradient norm, window RMS")
_M_OVER = _gauge("paddle_tpu_health_overhead_pct",
                 "monitor host cost as % of window wall time (EWMA)")

_ACTIVE = None  # weakref to the most recent monitor (dashboard/flight)


class HealthAnomalyError(RuntimeError):
    """Raised by HealthMonitor(action="raise") after ``max_consecutive``
    consecutive windows with a ``rewind_on`` anomaly."""


class HealthMonitor:
    """Device-folded per-layer gradient statistics on a check cadence.

    ::

        health = HealthMonitor(opt, check_every=25, ledger=ckpt_dir)

        @paddle.jit.to_static
        def step(x, y):
            _, loss = model(x, labels=y)
            loss.backward()
            opt.step()
            health.observe_grads()   # folded into the step program
            opt.clear_grad()
            return loss

        for i in range(steps):
            loss = step(x, y)
            health.observe(loss)     # device add, no sync
            health.check(i)          # one host pull per window

    ``action`` mirrors :class:`NaNSentinel`: ``"none"`` (default —
    anomalies are telemetry only), ``"rewind"`` (needs ``manager``;
    restores the last good checkpoint after ``max_consecutive``
    consecutive windows with a ``rewind_on`` anomaly and sets
    ``restored_step``), or ``"raise"``.
    """

    def __init__(self, optimizer, check_every: int = 25, *,
                 ledger=None, run_id=None, tokens_per_step=None,
                 manager=None, action: str = "none",
                 rewind_on=("grad_explosion", "loss_spike"),
                 max_consecutive: int = 3, warmup_windows: int = 3,
                 ewma_alpha: float = 0.2, loss_spike_z: float = 6.0,
                 grad_explode_abs: float = 1e4,
                 grad_explode_ratio: float = 10.0,
                 grad_vanish_abs: float = 1e-10, dead_abs: float = 0.0,
                 update_ratio_min: float = 1e-8,
                 update_ratio_max: float = 1e-1, history: int = 256):
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        if action not in ("none", "rewind", "raise"):
            raise ValueError(f"unknown action {action!r}")
        if action == "rewind" and manager is None:
            raise ValueError('action="rewind" needs a CheckpointManager')
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        self._opt = optimizer
        self.check_every = check_every
        self.manager = manager
        self.action = action
        self.rewind_on = tuple(rewind_on)
        self.max_consecutive = max_consecutive
        self.warmup_windows = warmup_windows
        self.ewma_alpha = float(ewma_alpha)
        self.loss_spike_z = float(loss_spike_z)
        self.grad_explode_abs = float(grad_explode_abs)
        self.grad_explode_ratio = float(grad_explode_ratio)
        self.grad_vanish_abs = float(grad_vanish_abs)
        self.dead_abs = float(dead_abs)
        self.update_ratio_min = float(update_ratio_min)
        self.update_ratio_max = float(update_ratio_max)
        self.tokens_per_step = tokens_per_step
        self.ledger = ledger if isinstance(ledger, (StepLedger, type(None))) \
            else StepLedger(ledger, run_id=run_id)

        self._params = list(optimizer._parameter_list)
        names, seen = [], set()
        for i, p in enumerate(self._params):
            n = getattr(p, "name", None) or f"param_{i}"
            if n in seen:
                n = f"{n}#{i}"
            seen.add(n)
            names.append(n)
        self._names = names
        self._shapes = [(tuple(p._data.shape), p._data.dtype)
                       for p in self._params]
        # reuse the clip's already-computed global norm instead of a second
        # device reduction (only ClipGradByGlobalNorm carries the attr)
        clip = getattr(optimizer, "_grad_clip", None)
        self._use_extern = clip is not None and \
            hasattr(clip, "last_global_norm")
        n = len(self._params)
        # the stacked stats accumulator — ordinary Tensors, so an enclosing
        # to_static trace lifts them into the step program's state set
        # (the fused-optimizer tracing machinery), exactly like moments.
        # Row n+1 col 0 counts fold applications DEVICE-side: under a
        # to_static trace the python body runs once, so a host counter
        # cannot know how many times the compiled program folded
        self._acc_t = Tensor(jnp.zeros((n + 2, 2), jnp.float32))
        self._loss_t = Tensor(jnp.zeros((), jnp.float32))
        self._jit_fold = None
        self._fold_traced = False

        self.windows = 0
        self.host_pulls = 0
        self.fold_dispatches = 0
        self.restored_step: int | None = None
        self.anomaly_counts: dict = {}
        self.stats: dict | None = None
        self.history = collections.deque(maxlen=history)
        self._grad_steps = 0
        self._loss_steps = 0
        self._consecutive = 0
        self._ew_loss = None
        self._ew_loss_var = 0.0
        self._ew_gnorm = None
        self.overhead_pct = 0.0
        self._cost_s = 0.0
        self.compile_s = 0.0
        self.total_cost_s = 0.0
        self._win_t0 = time.perf_counter()
        self._lock = _tsan.lock("health.monitor")
        global _ACTIVE
        _ACTIVE = weakref.ref(self)

    # -- hot path (device only) ----------------------------------------------

    def _fold(self, acc, lr, grads, params, *ext):
        """One window-fold step over the stacked accumulator: rows
        0..n-1 are per-parameter [grad_sq (summed over the window),
        param_sq (last)], row n is [global grad_sq (summed), lr (last)],
        row n+1 is [fold count (summed), 0]. Pure jnp, so it inlines
        under a to_static trace and jits for the eager path."""
        import jax.numpy as jnp
        gsq = jnp.stack([jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in grads])
        psq = jnp.stack([jnp.sum(jnp.square(p.astype(jnp.float32)))
                         for p in params])
        if ext:
            # clip-provided global norm; negative sentinel = not available
            # this step (e.g. the very first observe before any clip ran)
            e = ext[0].astype(jnp.float32)
            g_glob = jnp.where(e >= 0, jnp.square(e), jnp.sum(gsq))
        else:
            g_glob = jnp.sum(gsq)
        col0 = acc[:, 0] + jnp.concatenate(
            [gsq, g_glob[None], jnp.ones((1,), jnp.float32)])
        col1 = jnp.concatenate([psq, lr.astype(jnp.float32)[None],
                                jnp.zeros((1,), jnp.float32)])
        return jnp.stack([col0, col1], axis=1)

    def _extern_norm(self, tracing):
        import jax
        import jax.numpy as jnp
        v = getattr(self._opt._grad_clip, "last_global_norm", None)
        if v is not None and isinstance(v, jax.core.Tracer) and not tracing:
            v = None  # stale tracer left by a completed trace
        return jnp.asarray(-1.0, jnp.float32) if v is None else v

    def observe_grads(self) -> None:
        """Fold this step's gradient/parameter statistics into the device
        accumulator. Call after ``optimizer.step()`` (so a global-norm
        clip's computed norm is available) and before ``clear_grad()``.
        Device-side only — inlined under to_static, one jitted dispatch
        eagerly."""
        from ...jit.api import _trace_state
        tracing = getattr(_trace_state, "active", False)
        t0 = 0.0 if tracing else time.perf_counter()
        import jax.numpy as jnp
        grads = []
        for i, p in enumerate(self._params):
            g = p._grad
            if g is not None:
                grads.append(g._data)
            else:
                shape, dtype = self._shapes[i]
                grads.append(jnp.zeros(shape, dtype))
        params = [p._data for p in self._params]
        lr = self._opt._lr_tensor._data
        ext = (self._extern_norm(tracing),) if self._use_extern else ()
        acc = self._acc_t._data
        if tracing:
            new = self._fold(acc, lr, grads, params, *ext)
            self._fold_traced = True
        else:
            first = self._jit_fold is None
            if first:
                import jax
                self._jit_fold = jax.jit(self._fold)
            new = self._jit_fold(acc, lr, grads, params, *ext)
            self.fold_dispatches += 1
        self._acc_t._data = new
        self._grad_steps += 1
        if not tracing:
            dt = time.perf_counter() - t0
            if first:
                # one-time jit trace+compile: not steady-state overhead
                self.compile_s += dt
            else:
                self._cost_s += dt

    def observe(self, loss) -> None:
        """Fold this step's loss into the window accumulator — one
        device-side add, safe to call every step (and outside the jitted
        step, so it sees the loss the rest of the loop sees)."""
        from ...jit.api import _trace_state
        tracing = getattr(_trace_state, "active", False)
        t0 = 0.0 if tracing else time.perf_counter()
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        arr = loss._data if isinstance(loss, Tensor) else jnp.asarray(loss)
        self._loss_t._data = self._loss_t._data + \
            jnp.mean(arr.astype(jnp.float32))
        self._loss_steps += 1
        if not tracing:
            self._cost_s += time.perf_counter() - t0

    # -- cadence path (one host sync per window) -----------------------------

    def should_check(self, step: int) -> bool:
        return (step + 1) % self.check_every == 0

    def check(self, step: int, model=None, optimizer=None,
              lr_scheduler=None, dataloader=None) -> str | None:
        """Off-cadence: None, untouched. On cadence: the window's single
        host pull, rule evaluation, metric/flight/ledger export. Returns
        None (clean), "anomaly" (rules fired, telemetry only), "rewind"
        (escalated through the checkpoint manager)."""
        from ...jit.api import _trace_state
        if getattr(_trace_state, "active", False):
            return None  # never pull host-side state mid-trace
        if not self.should_check(step):
            return None
        if self._grad_steps == 0 and self._loss_steps == 0 \
                and not self._fold_traced:
            return None
        import numpy as np
        import jax.numpy as jnp
        n = len(self._params)
        combined = jnp.concatenate(
            [self._acc_t._data.ravel(), self._loss_t._data[None]])
        # Drain first, UNBILLED: blocking here waits out the window's
        # still-in-flight async step programs — pipeline time the loop
        # pays at its next sync anyway, not monitor cost (the continuous
        # profiler's pipeline-aware floor, applied to the pull).
        try:
            combined.block_until_ready()
        except AttributeError:
            pass
        t0 = time.perf_counter()
        wall_w = max(t0 - self._win_t0, 1e-9)
        a = np.asarray(combined)        # THE one batched host sync
        self.host_pulls += 1
        _M_PULLS.inc()
        acc = a[:-1].reshape(n + 2, 2)
        loss_sum = float(a[-1])
        # the device-side fold count is the one source of truth: under a
        # to_static trace the python body ran once, however many times the
        # compiled program actually folded
        gsteps, lsteps = int(round(float(acc[n + 1, 0]))), self._loss_steps
        # fresh zeros each window (never reuse a cached array: an enclosing
        # donate_state program may have consumed the old buffer)
        self._acc_t._data = jnp.zeros((n + 2, 2), jnp.float32)
        self._loss_t._data = jnp.zeros((), jnp.float32)
        self._grad_steps = 0
        self._loss_steps = 0
        if gsteps == 0 and lsteps == 0:
            self._win_t0 = time.perf_counter()
            return None  # empty window (step program never ran)

        stats = self._window_stats(step, acc, loss_sum, gsteps, lsteps,
                                   wall_w)
        anomalies = self._run_rules(stats)
        stats["anomalies"] = [x["rule"] for x in anomalies]
        self._update_ewma(stats)
        self._export(stats, anomalies)
        row = {k: stats.get(k) for k in
               ("step", "wall", "window_steps", "loss", "lr", "grad_norm",
                "param_norm", "update_ratio", "step_ms", "tokens_per_s",
                "anomalies")}
        with self._lock:
            self.stats = stats
            self.history.append(row)
            self.windows += 1
            for x in anomalies:
                self.anomaly_counts[x["rule"]] = \
                    self.anomaly_counts.get(x["rule"], 0) + 1
        _M_WINDOWS.inc()
        if self.ledger is not None:
            self.ledger.append(dict(
                row,
                peak_hbm_bytes=_peak_hbm(),
                retraces=int(_total(
                    "paddle_tpu_jit_trace_cache_retraces_total"))))
        # overhead accounting: everything this monitor cost on the host
        # this window (fold dispatch enqueues + this check) over wall time
        cost = self._cost_s + (time.perf_counter() - t0)
        self._cost_s = 0.0
        self.total_cost_s += cost
        pct = 100.0 * cost / wall_w
        self.overhead_pct = pct if self.windows == 1 \
            else 0.5 * self.overhead_pct + 0.5 * pct
        _M_OVER.set(self.overhead_pct)
        self._win_t0 = time.perf_counter()
        return self._escalate(step, anomalies, model, optimizer,
                              lr_scheduler, dataloader)

    # -- window math ---------------------------------------------------------

    def _window_stats(self, step, acc, loss_sum, gsteps, lsteps, wall_w):
        import numpy as np
        n = len(self._params)
        stats = {"step": int(step), "wall": time.time(),
                 "window_steps": int(gsteps or lsteps),
                 "step_ms": round(wall_w / max(gsteps, lsteps, 1) * 1e3, 4),
                 "tokens_per_s": None, "loss": None, "lr": None,
                 "grad_norm": None, "param_norm": None,
                 "update_ratio": None, "layers": {}}
        if self.tokens_per_step:
            stats["tokens_per_s"] = round(
                self.tokens_per_step * max(gsteps, lsteps) / wall_w, 2)
        if lsteps:
            stats["loss"] = loss_sum / lsteps
        if gsteps:
            layer_gn = np.sqrt(np.maximum(acc[:n, 0], 0.0) / gsteps)
            layer_pn = np.sqrt(np.maximum(acc[:n, 1], 0.0))
            gnorm = float(np.sqrt(np.maximum(acc[n, 0], 0.0) / gsteps))
            pnorm = float(np.sqrt(np.maximum(np.sum(acc[:n, 1]), 0.0)))
            lr = float(acc[n, 1])
            stats["lr"] = lr
            stats["grad_norm"] = gnorm
            stats["param_norm"] = pnorm
            stats["update_ratio"] = lr * gnorm / (pnorm + 1e-12)
            stats["layers"] = {
                name: {"grad_norm": float(layer_gn[i]),
                       "param_norm": float(layer_pn[i]),
                       "update_ratio":
                           lr * float(layer_gn[i]) /
                           (float(layer_pn[i]) + 1e-12)}
                for i, name in enumerate(self._names)}
        return stats

    def _run_rules(self, s):
        out = []
        warm = self.windows >= self.warmup_windows
        loss, gn = s["loss"], s["grad_norm"]
        pn, ur = s["param_norm"], s["update_ratio"]
        if loss is not None:
            if not math.isfinite(loss):
                out.append({"rule": "loss_spike", "loss": loss})
            elif warm and self._ew_loss is not None:
                std = math.sqrt(max(self._ew_loss_var, 1e-12))
                z = (loss - self._ew_loss) / std
                if z > self.loss_spike_z:
                    out.append({"rule": "loss_spike", "loss": loss,
                                "z": round(z, 2),
                                "ewma": round(self._ew_loss, 6)})
        if gn is not None:
            if not math.isfinite(gn) or gn > self.grad_explode_abs:
                out.append({"rule": "grad_explosion", "grad_norm": gn})
            elif warm and self._ew_gnorm and \
                    gn > self.grad_explode_ratio * self._ew_gnorm:
                out.append({"rule": "grad_explosion", "grad_norm": gn,
                            "ewma": round(self._ew_gnorm, 6)})
            if math.isfinite(gn) and gn < self.grad_vanish_abs and \
                    (pn or 0.0) > 0.0:
                out.append({"rule": "grad_vanish", "grad_norm": gn})
            if math.isfinite(gn) and gn > 0.0:
                dead = [name for name, d in s["layers"].items()
                        if d["grad_norm"] <= self.dead_abs]
                if dead:
                    out.append({"rule": "dead_layer", "count": len(dead),
                                "layers": dead[:8]})
        if ur is not None and math.isfinite(ur) and warm and \
                (ur > self.update_ratio_max or
                 (ur < self.update_ratio_min and (gn or 0.0) > 0.0)):
            out.append({"rule": "update_ratio_oob", "update_ratio": ur})
        return out

    def _update_ewma(self, s):
        a = self.ewma_alpha
        loss, gn = s["loss"], s["grad_norm"]
        if loss is not None and math.isfinite(loss):
            if self._ew_loss is None:
                self._ew_loss, self._ew_loss_var = loss, 0.0
            else:
                d = loss - self._ew_loss
                self._ew_loss += a * d
                self._ew_loss_var = (1 - a) * (self._ew_loss_var + a * d * d)
        if gn is not None and math.isfinite(gn):
            self._ew_gnorm = gn if self._ew_gnorm is None \
                else (1 - a) * self._ew_gnorm + a * gn

    def _export(self, s, anomalies):
        for gauge, key in ((_M_GRAD, "grad_norm"), (_M_PARAM, "param_norm"),
                           (_M_RATIO, "update_ratio"), (_M_LOSS, "loss")):
            v = s.get(key)
            if v is not None and math.isfinite(v):
                gauge.set(v)
        for name, d in s["layers"].items():
            if math.isfinite(d["grad_norm"]):
                _M_LAYER.set(d["grad_norm"], layer=name)
        for x in anomalies:
            _M_ANOM.inc(rule=x["rule"])
            if _flight.enabled():
                _flight.record("health_anomaly", step=s["step"], **x)

    def _escalate(self, step, anomalies, model, optimizer, lr_scheduler,
                  dataloader):
        hit = any(x["rule"] in self.rewind_on for x in anomalies)
        if not hit:
            self._consecutive = 0
            return "anomaly" if anomalies else None
        self._consecutive += 1
        if self.action == "none" or self._consecutive < self.max_consecutive:
            return "anomaly"
        self._consecutive = 0
        if self.action == "raise":
            _flight.record("health_raise", step=int(step),
                           rules=[x["rule"] for x in anomalies])
            _flight.dump(reason="health_raise", step=int(step),
                         dump_dir=getattr(self.manager, "root", None))
            raise HealthAnomalyError(
                f"{[x['rule'] for x in anomalies]} persisted for "
                f"{self.max_consecutive} consecutive windows (step {step})")
        restored = self.manager.restore(
            model=model, optimizer=optimizer or self._opt,
            lr_scheduler=lr_scheduler, dataloader=dataloader)
        if restored is None:
            return "anomaly"  # advisory tier: no target, no crash
        self.restored_step = restored
        self.on_restore(restored)
        _flight.record("health_rewind", step=int(step),
                       restored_step=int(restored))
        _flight.dump(reason="health_rewind", step=int(step),
                     dump_dir=self.manager.root)
        return "rewind"

    # -- lifecycle -----------------------------------------------------------

    def reset_window(self) -> None:
        """Drop the in-flight window accumulator (stale timeline — e.g.
        after an external rewind restored older weights)."""
        import jax.numpy as jnp
        n = len(self._params)
        self._acc_t._data = jnp.zeros((n + 2, 2), jnp.float32)
        self._loss_t._data = jnp.zeros((), jnp.float32)
        self._grad_steps = 0
        self._loss_steps = 0
        self._cost_s = 0.0
        self._win_t0 = time.perf_counter()

    def on_restore(self, step) -> None:
        """Checkpoint-restore hook (CheckpointManager.restore(health=...)):
        the run's timeline just rewound, so the window in flight is from
        an abandoned future — drop it."""
        self.reset_window()
        self._consecutive = 0

    def snapshot(self) -> dict:
        """Plain-dict summary for bench telemetry / flight dumps."""
        with self._lock:
            last = dict(self.stats) if self.stats else None
            counts = dict(self.anomaly_counts)
        if last is not None:
            last.pop("layers", None)
        return {"windows": self.windows, "host_pulls": self.host_pulls,
                "fold_dispatches": self.fold_dispatches,
                "check_every": self.check_every,
                "params": len(self._params),
                "uses_clip_norm": self._use_extern,
                "overhead_pct": round(self.overhead_pct, 4),
                "anomalies": counts, "last": last}


def _peak_hbm():
    from ..memory import device_memory_stats
    v = int(device_memory_stats().get("peak_bytes_in_use", 0))
    return v or None


def get_monitor() -> HealthMonitor | None:
    """The most recently constructed monitor, if still alive."""
    return _ACTIVE() if _ACTIVE is not None else None


def snapshot_for_flight():
    """Guarded monitor summary for flight dumps (None when no monitor)."""
    try:
        m = get_monitor()
        return m.snapshot() if m is not None else None
    except Exception:
        return None
