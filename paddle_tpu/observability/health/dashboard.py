"""Zero-dependency HTML dashboard for the telemetry server's
``/dashboard`` route: inline SVG sparklines over the active
HealthMonitor's window history plus the live step-series ledger tail,
and a counters strip from the metrics registry. Pure stdlib string
assembly — nothing to install, safe inside a training process."""

from __future__ import annotations

import html
import math
import time

from ..metrics import total as _total

_CARDS = (("loss", "window-mean loss", "#b83280"),
          ("grad_norm", "global grad norm", "#2b6cb0"),
          ("update_ratio", "update ratio lr·|g|/|p|", "#2f855a"),
          ("step_ms", "step wall (ms)", "#975a16"),
          ("tokens_per_s", "tokens / s", "#6b46c1"))

_COUNTERS = (("windows", "paddle_tpu_health_windows_total"),
             ("anomalies", "paddle_tpu_health_anomalies_total"),
             ("host pulls", "paddle_tpu_health_host_pulls_total"),
             ("retraces", "paddle_tpu_jit_trace_cache_retraces_total"),
             ("nan windows", "paddle_tpu_resilience_nan_events_total"))

_CSS = """
body{font:14px/1.45 system-ui,sans-serif;margin:1.2em;background:#fafafa;
color:#1a202c}
h1{font-size:1.25em;margin:0 0 .2em}
.sub{color:#718096;margin-bottom:1em}
.cards{display:flex;flex-wrap:wrap;gap:12px}
.card{background:#fff;border:1px solid #e2e8f0;border-radius:8px;
padding:10px 14px;min-width:280px}
.card h2{font-size:.85em;margin:0 0 4px;color:#4a5568;font-weight:600}
.card .v{font-size:1.15em;font-weight:700}
.counters{display:flex;gap:18px;margin:1em 0;flex-wrap:wrap}
.counters div{background:#edf2f7;border-radius:6px;padding:6px 12px}
table{border-collapse:collapse;margin-top:.5em}
td,th{padding:3px 10px;border-bottom:1px solid #e2e8f0;text-align:right}
th{color:#4a5568}td:first-child,th:first-child{text-align:left}
.anom{color:#c53030;font-weight:600}
"""


def _spark(vals, width=260, height=48, color="#2b6cb0"):
    """Inline SVG sparkline of a numeric series (non-finite points are
    dropped; <2 points renders a placeholder)."""
    pts = [(i, v) for i, v in enumerate(vals)
           if isinstance(v, (int, float)) and math.isfinite(v)]
    if len(pts) < 2:
        return (f'<svg width="{width}" height="{height}">'
                f'<text x="4" y="{height // 2}" fill="#a0aec0" '
                f'font-size="11">waiting for data…</text></svg>')
    lo = min(v for _, v in pts)
    hi = max(v for _, v in pts)
    span = (hi - lo) or 1.0
    x0, xn = pts[0][0], pts[-1][0]
    xs = (xn - x0) or 1
    coords = " ".join(
        f"{(i - x0) / xs * (width - 4) + 2:.1f},"
        f"{height - 4 - (v - lo) / span * (height - 8):.1f}"
        for i, v in pts)
    return (f'<svg width="{width}" height="{height}" class="sparkline">'
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{coords}"/></svg>')


def _fmt(v):
    if v is None:
        return "–"
    if isinstance(v, float):
        return f"{v:.5g}"
    return html.escape(str(v))


def _ledger_tail(mon, last):
    if mon is None or mon.ledger is None:
        return []
    try:
        from .ledger import read_ledger
        _, rows = read_ledger(mon.ledger.path)
        return rows[-last:]
    except Exception:
        return []


def render_dashboard(last: int = 180) -> str:
    """The full /dashboard page as a string (auto-refreshes)."""
    from . import get_monitor
    mon = get_monitor()
    parts = ['<!doctype html><html><head><meta charset="utf-8">',
             '<meta http-equiv="refresh" content="5">',
             '<title>paddle_tpu training health</title>',
             f'<style>{_CSS}</style></head><body>',
             '<h1>Training health</h1>']
    if mon is None:
        parts.append('<p class="sub">no active HealthMonitor in this '
                     'process — attach one to the train loop to light '
                     'this page up</p>')
        hist, stats = [], None
    else:
        with mon._lock:
            hist = list(mon.history)[-last:]
            stats = dict(mon.stats) if mon.stats else None
        snap = mon.snapshot()
        parts.append(
            f'<p class="sub">windows {snap["windows"]} · check every '
            f'{snap["check_every"]} steps · {snap["params"]} params · '
            f'overhead {snap["overhead_pct"]:.3f}% · anomalies '
            f'{sum(snap["anomalies"].values()) or 0}</p>')
    parts.append('<div class="counters">')
    for label, name in _COUNTERS:
        parts.append(f'<div>{label}: <b>{int(_total(name))}</b></div>')
    parts.append('</div><div class="cards">')
    for key, title, color in _CARDS:
        series = [r.get(key) for r in hist]
        lastv = next((v for v in reversed(series)
                      if isinstance(v, (int, float)) and math.isfinite(v)),
                     None)
        parts.append(f'<div class="card"><h2>{title}</h2>'
                     f'<div class="v">{_fmt(lastv)}</div>'
                     f'{_spark(series, color=color)}</div>')
    parts.append('</div>')
    if stats and stats.get("layers"):
        top = sorted(stats["layers"].items(),
                     key=lambda kv: -(kv[1]["grad_norm"]
                                      if math.isfinite(kv[1]["grad_norm"])
                                      else float("inf")))[:12]
        parts.append('<h2 style="font-size:1em">top layers by grad norm '
                     f'(window @ step {stats["step"]})</h2>'
                     '<table><tr><th>layer</th><th>grad norm</th>'
                     '<th>param norm</th><th>update ratio</th></tr>')
        for name, d in top:
            parts.append(
                f'<tr><td>{html.escape(name)}</td>'
                f'<td>{_fmt(d["grad_norm"])}</td>'
                f'<td>{_fmt(d["param_norm"])}</td>'
                f'<td>{_fmt(d["update_ratio"])}</td></tr>')
        parts.append('</table>')
    recent = [r for r in hist if r.get("anomalies")][-8:]
    if recent:
        parts.append('<h2 style="font-size:1em">recent anomalies</h2><ul>')
        for r in reversed(recent):
            parts.append(f'<li class="anom">step {r["step"]}: '
                         f'{html.escape(", ".join(r["anomalies"]))}</li>')
        parts.append('</ul>')
    tail = _ledger_tail(mon, last)
    if tail:
        parts.append(f'<p class="sub">ledger: {html.escape(mon.ledger.path)}'
                     f' · {len(tail)} windows shown</p>')
    parts.append(f'<p class="sub">rendered {time.strftime("%H:%M:%S")}</p>'
                 '</body></html>')
    return "".join(parts)
