"""Runtime telemetry registry: Counter / Gauge / Histogram.

Zero-dependency by design (stdlib only — no jax, no numpy): every hot layer
of the framework (jit dispatch, collectives, the dataloader, profiler spans)
imports this module at its own import time, so it must never pull the
accelerator stack in or add measurable import cost.

Naming convention: ``paddle_tpu_<area>_<name>_<unit>`` — e.g.
``paddle_tpu_jit_trace_cache_misses_total``, ``paddle_tpu_io_batch_wait_seconds``.
Counters end in ``_total``; histograms and gauges end in their unit.

Overhead contract: when disabled (``PADDLE_TPU_METRICS=0`` in the
environment, or ``enable(False)`` at runtime) every mutation method returns
after a single attribute load + bool test — no locking, no dict access —
so instrumentation can stay in hot paths unconditionally.

Thread safety: each metric owns one lock protecting its label->value table;
registries own a lock for get-or-create. Reads used by exporters copy under
the same lock.

Cardinality guard: a per-metric series cap (``PADDLE_TPU_METRICS_MAX_SERIES``,
default 256) bounds the label table — per-qualname retrace counters and
per-span counters cannot grow without limit on pathological workloads.
Once a metric is at cap, samples for NEW label sets fold into a single
``overflow="true"`` sink series (existing series keep recording exactly),
and a one-time warning names the metric.
"""

from __future__ import annotations

import os
import re
import time
import warnings
from bisect import bisect_left
from collections import deque

# the concurrency tier's runtime half: tsan.py is stdlib-only and the
# package defers its linter machinery behind a module __getattr__, so
# the zero-dependency contract above holds (no jax/numpy, no rule
# engine on this import path)
from ..analysis.concurrency import tsan as _tsan

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "DEFAULT_BUCKETS",
    "DEFAULT_MAX_SERIES", "OVERFLOW_KEY",
    "get_registry", "counter", "gauge", "histogram",
    "enabled", "enable", "value", "total", "reset",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: label set every over-cap sample folds into
OVERFLOW_KEY = (("overflow", "true"),)

DEFAULT_MAX_SERIES = 256

#: windowed-rate history: one (monotonic, cumulative) snapshot at most
#: every RATE_TICK_S per labeled series, RATE_SLOTS deep, so rate()/
#: delta() can window ~RATE_TICK_S * RATE_SLOTS = 64s of history —
#: enough for the /healthz 30s steps/s window with slack.
RATE_TICK_S = 0.25
RATE_SLOTS = 256

#: injectable clock (tests patch this; monotonic so wall-clock jumps
#: cannot produce negative windows)
_monotonic = time.monotonic


def _env_max_series() -> int:
    try:
        return max(int(os.environ.get("PADDLE_TPU_METRICS_MAX_SERIES",
                                      DEFAULT_MAX_SERIES)), 1)
    except ValueError:
        return DEFAULT_MAX_SERIES


def _env_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_METRICS", "1").lower() not in (
        "0", "false", "off")


class _State:
    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = _env_enabled()


_state = _State()


def enabled() -> bool:
    """True while telemetry collection is on (``PADDLE_TPU_METRICS`` env,
    overridable at runtime via :func:`enable`)."""
    return _state.enabled


def enable(flag: bool = True) -> bool:
    """Turn collection on/off process-wide; returns the new state."""
    _state.enabled = bool(flag)
    return _state.enabled


def _label_key(labels: dict) -> tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _mutation_key(labels: dict) -> tuple:
    """Label key for WRITE paths: the ``overflow`` label is reserved for
    the cardinality-guard sink — user data recorded under it would mix
    indistinguishably with folded over-cap spill. Reads (``value()``)
    stay permitted so the sink is queryable."""
    if labels and "overflow" in labels:
        raise ValueError(
            "label name 'overflow' is reserved for the cardinality-guard "
            "sink series")
    return _label_key(labels)


class MetricBase:
    """Shared storage: a lock-guarded ``{sorted-label-tuple: value}`` table."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", windowed: bool = False):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        # rate()/delta() history is OPT-IN: every tick costs a clock read
        # plus ring upkeep on the mutation path, and most of the registry's
        # hot counters (collective bytes, retraces, prefetch) are only ever
        # scraped cumulatively
        self.windowed = bool(windowed)
        self._lock = _tsan.lock(f"metrics.{name}")
        self._values: dict = {}
        self._ticks: dict = {}   # key -> deque[(monotonic, cumulative)]
        self.max_series = _env_max_series()
        self._overflowed = False

    def _slot(self, key: tuple) -> tuple:
        """Cardinality guard; call under ``self._lock``. Existing series
        and under-cap inserts pass through; a NEW label set on a metric at
        cap folds into :data:`OVERFLOW_KEY` (the sink series itself is
        exempt from the cap, so the spill is never dropped)."""
        if key in self._values or len(self._values) < self.max_series \
                or key == OVERFLOW_KEY:
            return key
        if not self._overflowed:
            self._overflowed = True
            warnings.warn(
                f"metric {self.name!r} hit its label-cardinality cap "
                f"({self.max_series} series; PADDLE_TPU_METRICS_MAX_SERIES); "
                f'new label sets now fold into the overflow="true" series',
                RuntimeWarning, stacklevel=3)
        return OVERFLOW_KEY

    def clear(self):
        with self._lock:
            self._values.clear()
            self._ticks.clear()

    # -- windowed rates (Counter/Histogram opt in via _cum_of) ---------------

    def _note_tick(self, key: tuple, cum: float):
        """Under ``self._lock``: snapshot the cumulative value for the
        rate window (``windowed=True`` metrics only). Snapshots within
        RATE_TICK_S of the last collapse into it (value updated, timestamp
        kept) so a hot series costs one clock read per mutation, not one
        ring slot."""
        if not self.windowed:
            return
        dq = self._ticks.get(key)
        if dq is None:
            dq = self._ticks[key] = deque(maxlen=RATE_SLOTS)
        now = _monotonic()
        if dq and now - dq[-1][0] < RATE_TICK_S:
            dq[-1] = (dq[-1][0], cum)
        else:
            dq.append((now, cum))

    def _window_base(self, key: tuple, window: float):
        """Under ``self._lock``: (base_time, base_value) — the newest
        snapshot at least ``window`` old, else the oldest available
        (partial window). None when no history exists."""
        dq = self._ticks.get(key)
        if not dq:
            return None
        now = _monotonic()
        base = dq[0]
        for t, v in reversed(dq):
            if now - t >= window:
                base = (t, v)
                break
        return base

    def _windowed(self, window: float, labels: dict):
        """(delta, elapsed_seconds) of the cumulative value over (up to)
        the last ``window`` seconds; (0.0, 0.0) without enough history."""
        key = _label_key(labels)
        with self._lock:
            base = self._window_base(key, window)
            if base is None:
                return 0.0, 0.0
            cum = self._cum_of(key)
        elapsed = _monotonic() - base[0]
        if elapsed <= 0:
            return 0.0, 0.0
        return max(cum - base[1], 0.0), elapsed

    def _cum_of(self, key: tuple) -> float:
        raise TypeError(
            f"windowed rate is not defined for {self.kind} metrics")

    def _items(self):
        with self._lock:
            return sorted(self._values.items())

    def series(self) -> list:
        """``[(labels_dict, value)]`` per labeled series — the structured
        form of ``snapshot()["values"]``, for consumers that would
        otherwise reverse-parse the formatted label strings."""
        return [(dict(k), v) for k, v in self._items()]

    def snapshot(self) -> dict:
        return {"type": self.kind, "help": self.help,
                "values": {_format_labels(k): v for k, v in self._items()}}


def _format_labels(key: tuple) -> str:
    """Stable string form of one label set for JSON snapshots: ``fn="f"``
    pairs joined by commas, empty string for the unlabeled series."""
    return ",".join(f'{k}="{v}"' for k, v in key)


class Counter(MetricBase):
    kind = "counter"

    def inc(self, value: float = 1, /, **labels):
        if not _state.enabled:
            return
        if value < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = _mutation_key(labels)
        with self._lock:
            key = self._slot(key)
            cum = self._values[key] = self._values.get(key, 0) + value
            self._note_tick(key, cum)

    def value(self, /, **labels):
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def total(self):
        with self._lock:
            return sum(self._values.values())

    def _cum_of(self, key):
        return self._values.get(key, 0)

    def rate(self, window: float = 60.0, /, **labels) -> float:
        """Counter increase per second over (up to) the last ``window``
        seconds — /healthz-grade steps/s without scrape-side math. Needs
        the history to actually span time: 0.0 with fewer than two
        snapshot ticks (resolution RATE_TICK_S, depth RATE_SLOTS)."""
        delta, elapsed = self._windowed(window, labels)
        return delta / elapsed if elapsed > 0 else 0.0

    def delta(self, window: float = 60.0, /, **labels) -> float:
        """Raw counter increase over (up to) the last ``window`` seconds
        (the un-divided form of :meth:`rate`)."""
        return self._windowed(window, labels)[0]


class Gauge(MetricBase):
    kind = "gauge"

    def set(self, value: float, /, **labels):
        if not _state.enabled:
            return
        key = _mutation_key(labels)
        with self._lock:
            key = self._slot(key)
            self._values[key] = value

    def inc(self, value: float = 1, /, **labels):
        if not _state.enabled:
            return
        key = _mutation_key(labels)
        with self._lock:
            key = self._slot(key)
            self._values[key] = self._values.get(key, 0) + value

    def dec(self, value: float = 1, /, **labels):
        self.inc(-value, **labels)

    def value(self, /, **labels):
        with self._lock:
            return self._values.get(_label_key(labels), 0)


# Prometheus-style latency buckets, in seconds.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(MetricBase):
    """Fixed-bucket histogram. Buckets are upper bounds (inclusive, the
    Prometheus ``le`` contract) plus an implicit +Inf overflow slot.
    Per-label storage is ``[per-bucket counts, sum, count]``; cumulative
    counts are materialized only at export time."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS,
                 windowed: bool = False):
        super().__init__(name, help, windowed=windowed)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs

    def observe(self, value: float, /, **labels):
        if not _state.enabled:
            return
        key = _mutation_key(labels)
        with self._lock:
            key = self._slot(key)
            row = self._values.get(key)
            if row is None:
                row = self._values[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]
            row[0][bisect_left(self.buckets, value)] += 1
            row[1] += value
            row[2] += 1
            self._note_tick(key, row[2])

    def value(self, /, **labels) -> dict:
        """``{"count", "sum", "buckets"}`` with CUMULATIVE bucket counts
        keyed by the ``le`` bound (``repr(float)`` form, plus ``+Inf``)."""
        with self._lock:
            row = self._values.get(_label_key(labels))
            if row is None:
                return {"count": 0, "sum": 0.0, "buckets": {}}
            counts, s, n = list(row[0]), row[1], row[2]
        out, acc = {}, 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out[repr(b)] = acc
        out["+Inf"] = acc + counts[-1]
        return {"count": n, "sum": s, "buckets": out}

    def quantile(self, q: float, /, **labels):
        """Approximate ``q``-quantile by linear interpolation inside the
        owning bucket (the Prometheus ``histogram_quantile`` estimate,
        anchored at 0 below the first bound). None when the series has no
        observations. Overflow-bucket hits return the top finite bound —
        a lower bound on the true quantile, still gate-worthy. Shared by
        bench.py's data-wait p50 and the serving ``timing_split`` p50s."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile wants 0 <= q <= 1, got {q}")
        v = self.value(**labels)
        n = v["count"]
        if not n:
            return None
        target = q * n
        prev_le, prev_acc = 0.0, 0
        for le, acc in v["buckets"].items():
            if le == "+Inf":
                continue
            bound = float(le)
            if acc >= target:
                span = acc - prev_acc
                frac = (target - prev_acc) / span if span else 1.0
                return prev_le + (bound - prev_le) * frac
            prev_le, prev_acc = bound, acc
        return prev_le

    def snapshot(self) -> dict:
        vals = {}
        with self._lock:
            keys = sorted(self._values)
        for k in keys:
            vals[_format_labels(k)] = self.value(**dict(k))
        return {"type": self.kind, "help": self.help,
                "buckets": [repr(b) for b in self.buckets], "values": vals}

    def _cum_of(self, key):
        row = self._values.get(key)
        return row[2] if row is not None else 0

    def rate(self, window: float = 60.0, /, **labels) -> float:
        """Observations per second over (up to) the last ``window``
        seconds (the continuous profiler's steps/s reads the step
        histogram this way). Same snapshot semantics as
        :meth:`Counter.rate`."""
        delta, elapsed = self._windowed(window, labels)
        return delta / elapsed if elapsed > 0 else 0.0

    def delta(self, window: float = 60.0, /, **labels) -> float:
        """Raw observation-count increase over (up to) the last
        ``window`` seconds."""
        return self._windowed(window, labels)[0]


_METRIC_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Get-or-create store of metrics by name. Creating the same name twice
    returns the existing object; asking for it under a different type
    raises (one name, one type — the Prometheus exposition contract)."""

    def __init__(self):
        self._lock = _tsan.lock("metrics.registry")
        self._metrics: dict[str, MetricBase] = {}

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}")
                want = kw.get("buckets")
                if want is not None and \
                        tuple(sorted(float(b) for b in want)) != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}, requested "
                        f"{tuple(sorted(float(b) for b in want))}")
                if kw.get("windowed") and not m.windowed:
                    # a later windowed=True request arms it. A PLAIN
                    # write on purpose: a monotonic one-way bool flip
                    # (worst case one missed rate tick) — taking
                    # m._lock here, inside the registry critical
                    # section, would mint a registry→metric lock order
                    # no other path needs
                    m.windowed = True
                return m
            kw = {k: v for k, v in kw.items() if v is not None}
            m = self._metrics[name] = cls(name, help, **kw)
            return m

    def counter(self, name: str, help: str = "",
                windowed: bool = False) -> Counter:
        return self._get_or_create(Counter, name, help,
                                   windowed=windowed or None)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets=None,
                  windowed: bool = False) -> Histogram:
        """Get-or-create a histogram. buckets=None accepts an existing
        metric's bounds (DEFAULT_BUCKETS when creating); explicit buckets
        must MATCH an already-registered metric's bounds or this raises —
        silently binning into bounds the caller never asked for would
        corrupt the data."""
        return self._get_or_create(Histogram, name, help, buckets=buckets,
                                   windowed=windowed or None)

    def get(self, name: str) -> MetricBase | None:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> list[MetricBase]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """JSON-safe ``{name: metric.snapshot()}``, names sorted. Series
        that never recorded a sample are omitted (a registered-but-silent
        metric carries no information and would bloat bench JSON lines)."""
        out = {}
        for m in self.metrics():
            snap = m.snapshot()
            if snap["values"]:
                out[m.name] = snap
        return out

    def value(self, name: str, /, **labels):
        m = self.get(name)
        if m is None:
            return 0
        return m.value(**labels)

    def total(self, name: str):
        """Sum of a counter across all label sets (0 for unknown names)."""
        m = self.get(name)
        if m is None:
            return 0
        if isinstance(m, Counter):
            return m.total()
        raise TypeError(f"total() is only defined for counters, "
                        f"{name!r} is a {m.kind}")

    def reset(self):
        """Zero every metric's samples; registered metric OBJECTS survive,
        so module-level handles held by instrumentation stay live."""
        for m in self.metrics():
            m.clear()


_default_registry = Registry()


def get_registry() -> Registry:
    """The process-wide default registry all framework instrumentation
    records into."""
    return _default_registry


def counter(name: str, help: str = "", windowed: bool = False) -> Counter:
    return _default_registry.counter(name, help, windowed=windowed)


def gauge(name: str, help: str = "") -> Gauge:
    return _default_registry.gauge(name, help)


def histogram(name: str, help: str = "", buckets=None,
              windowed: bool = False) -> Histogram:
    return _default_registry.histogram(name, help, buckets=buckets,
                                       windowed=windowed)


def value(name: str, /, **labels):
    return _default_registry.value(name, **labels)


def total(name: str):
    return _default_registry.total(name)


def reset():
    _default_registry.reset()
