"""Continuous profiler: always-on, bounded-overhead measured attribution.

The missing half of the observability stack: PR 1/PR 4 count events and
attribute memory, PR 6 *estimates* fusion wins statically — nothing until
now measured where device time actually goes while a run is alive. This
package closes the loop:

* :class:`ContinuousProfiler` — a sampling profiler the training loop
  drives with one ``on_step()`` call per step. Every
  ``PADDLE_TPU_PROF_EVERY`` steps (default 50) it opens a one-step
  **capture window**: the framework's dispatch sites (``to_static``
  program execution, the fused optimizer step, collective ``wait()``\\ s,
  ``prefetch_to_device`` feed waits) time themselves and record into
  per-program ``paddle_tpu_program_step_ms`` histograms. Outside a window
  the hooks cost one boolean test. The sampler measures its OWN cost —
  the profiled step's excess over the steady-state EWMA plus its direct
  bookkeeping — amortizes it over the cadence, exports it as
  ``paddle_tpu_prof_overhead_pct``, and **backs its cadence off**
  (doubling ``every``) whenever it exceeds the hard budget
  ``PADDLE_TPU_PROF_BUDGET_PCT`` (default 1%).
* :func:`fusion_targets` — the reconciliation layer: re-runs the PR 6
  graph analyzer on each profiled ``to_static`` program (via
  ``StaticFunction.analyze_cached``, an abstract trace — no device
  execution) and joins the static GA100 fusion candidates with the
  program's MEASURED ms/step and the window's measured HBM delta
  (``observability.memory``), emitting the ranked mega-kernel work queue
  (``bench.py`` ``extra.fusion_targets``; appended to flight dumps).
* :func:`serve` — a zero-dependency threaded HTTP server
  (``PADDLE_TPU_METRICS_PORT``) exposing ``/metrics`` (Prometheus text),
  ``/healthz`` (step liveness), ``/flight`` (the ring buffer as JSON) and
  ``/profile?steps=N`` (trigger a dense on-demand capture window).

Import-time stdlib-only, like the rest of the package: jax and the graph
analyzer are pulled in lazily, only inside reconciliation.

CLI: ``python -m paddle_tpu.observability.continuous report`` renders the
reconciled fusion-target table (live tiny-GPT run, or ``--from-bench``).
Disable the sampler entirely with ``PADDLE_TPU_PROF=0``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref

from ...analysis.concurrency import tsan as _tsan
from .. import metrics as _m

__all__ = [
    "ContinuousProfiler", "DEFAULT_EVERY", "DEFAULT_BUDGET_PCT",
    "MAX_EVERY", "PROGRAM_MS_BUCKETS",
    "get_profiler", "profiler_if_started", "on_step", "stop", "reset",
    "sampling_active", "record_program", "note_program",
    "fusion_targets", "last_reconciliation",
    "last_unfused_reconciliation", "profile_snapshot",
    "serve", "shutdown_server", "TelemetryServer",
]

DEFAULT_EVERY = 50
DEFAULT_BUDGET_PCT = 1.0
#: backoff ceiling: even a pathologically expensive capture keeps at least
#: one window per MAX_EVERY steps, so telemetry never goes fully dark
MAX_EVERY = 6400

#: total on-demand windows that may be queued at once (request_capture
#: clamps to this): every pending window makes one future step's
#: dispatches block, budget-exempt — repeated /profile requests must not
#: be able to stack an unbounded slowdown
MAX_PENDING_CAPTURE = 1000

#: per-program latency buckets, in MILLISECONDS (the registry default is
#: seconds-scale; dispatch latencies need sub-ms resolution)
PROGRAM_MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                      50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_on(name, default="1"):
    return os.environ.get(name, default).lower() not in ("0", "false", "off")


class ContinuousProfiler:
    """Step-cadence sampling profiler with a hard overhead budget.

    One ``on_step()`` call per training step. The step AFTER a cadence
    hit is profiled: dispatch hooks (see module docstring) block on their
    results and record wall ms into ``paddle_tpu_program_step_ms{program=}``.
    The profiled step's excess over the steady-state EWMA — plus direct
    bookkeeping (the HBM probe) — is the sampler's cost; amortized over
    ``every`` steps it must stay under ``budget_pct`` of step time, or the
    cadence doubles (exported: ``paddle_tpu_prof_overhead_pct``,
    ``paddle_tpu_prof_cadence_steps``, ``paddle_tpu_prof_backoffs_total``).

    Reconciliation (the one deliberate exception to the budget): after
    ``RECONCILE_AFTER_WINDOWS`` windows the profiler re-runs the graph
    analyzer once per profiled program — an abstract re-trace, roughly one
    extra compile's worth of host time, amortizing to zero — so flight
    dumps and ``/flight`` carry the measured fusion-target table without
    any consumer having to ask. ``PADDLE_TPU_PROF_RECONCILE=0`` disables.
    """

    RECONCILE_AFTER_WINDOWS = 2
    RECONCILE_REFRESH_WINDOWS = 64

    def __init__(self, every: int | None = None,
                 budget_pct: float | None = None, registry=None):
        self.enabled = _env_on("PADDLE_TPU_PROF")
        self.every = max(every if every is not None
                         else _env_int("PADDLE_TPU_PROF_EVERY",
                                       DEFAULT_EVERY), 1)
        self.base_every = self.every
        self.budget_pct = budget_pct if budget_pct is not None \
            else _env_float("PADDLE_TPU_PROF_BUDGET_PCT", DEFAULT_BUDGET_PCT)
        self.memory_probe = _env_on("PADDLE_TPU_PROF_MEMORY")
        self.auto_reconcile = _env_on("PADDLE_TPU_PROF_RECONCILE")
        reg = registry or _m.get_registry()
        self._h_program = reg.histogram(
            "paddle_tpu_program_step_ms",
            "wall milliseconds per dispatched program inside profiled "
            "step windows, by program", buckets=PROGRAM_MS_BUCKETS)
        self._c_steps = reg.counter(
            "paddle_tpu_prof_steps_total",
            "training steps observed by the continuous profiler "
            "(on_step calls; /healthz derives steps/s from its rate)",
            windowed=True)
        self._c_windows = reg.counter(
            "paddle_tpu_prof_windows_total",
            "profiled capture windows, by trigger (cadence|on_demand)")
        self._c_backoffs = reg.counter(
            "paddle_tpu_prof_backoffs_total",
            "cadence doublings forced by the overhead budget")
        self._g_overhead = reg.gauge(
            "paddle_tpu_prof_overhead_pct",
            "measured sampler cost as percent of steady-state step time "
            "(amortized over the cadence; budget PADDLE_TPU_PROF_BUDGET_PCT)")
        self._g_every = reg.gauge(
            "paddle_tpu_prof_cadence_steps",
            "current sampling cadence (steps between capture windows)")
        self._g_every.set(self.every)
        self._clock = time.perf_counter   # injectable for tests
        # an RLock: on_step holds it across window close/open (so /healthz
        # snapshots and server-thread resets can never observe a window
        # mid-transition), and the window helpers re-enter it
        self._lock = _tsan.rlock("observability.continuous.profiler")
        self.active = False               # a capture window is open NOW
        self._pending = 0                 # dense steps requested (/profile)
        self._count = 0                   # on_step calls seen
        self._last_t = None               # previous on_step clock
        self._window_t0 = None
        self._window_trigger = "cadence"
        self._window: dict = {}           # name -> [calls, seconds]
        self._bytes_open = None
        self._open_cost = 0.0
        self.steady_step_s = None         # EWMA of UNPROFILED step wall
        self.overhead_pct = 0.0           # EWMA, exported
        self.windows = 0
        self.last_step: int | None = None
        self.last_step_wall: float | None = None   # time.time(), /healthz
        self.hbm_delta_bytes: int | None = None
        self._programs: dict = {}   # name -> {"ms", "calls", "windows"}
        self._static_fns: dict = {} # name -> weakref to StaticFunction
        self._reconciled_at = 0

    # -- the per-step driver -------------------------------------------------

    def on_step(self, step: int | None = None) -> None:
        """Mark a step boundary. Cheap (a clock read + a counter) except
        when it closes or opens a capture window. ``PADDLE_TPU_PROF=0``
        disables SAMPLING only — step liveness (last_step, steps/s, the
        /healthz contract) keeps updating, so turning the profiler off
        never silences stall alerting."""
        now = self._clock()
        want_reconcile = False
        with self._lock:
            self._count += 1
            self.last_step = step if step is not None else self._count
            self.last_step_wall = time.time()
        self._c_steps.inc()
        if not self.enabled:
            return
        with self._lock:
            if self.active:
                want_reconcile = self._close_window(now)
            elif self._last_t is not None:
                dt = now - self._last_t
                self.steady_step_s = dt if self.steady_step_s is None \
                    else 0.8 * self.steady_step_s + 0.2 * dt
            if self._pending > 0 or self._count % self.every == 1 \
                    or self.every == 1:
                self._open_window()
            self._last_t = self._clock()
        if want_reconcile:
            # deliberately OUTSIDE the lock: reconciliation re-traces
            # jaxprs (milliseconds of host work) and must not block
            # /healthz or server-thread snapshot() readers meanwhile
            try:
                from .reconcile import fusion_targets as _ft
                _ft(profiler=self)
            except Exception:
                pass
            with self._lock:
                # re-stamp so the reconcile's host milliseconds do not
                # ride the next inter-step dt into the steady-step EWMA
                # (which is the overhead accounting's cost floor)
                self._last_t = self._clock()

    def stop(self) -> None:
        """Close any open window WITHOUT folding it (the step it covers
        was cut short) and deactivate until the next ``on_step``. Call
        after a timed loop so later untimed work is not captured."""
        with self._lock:
            self.active = False
            self._window = {}
            self._window_t0 = None

    def request_capture(self, steps: int = 1) -> int:
        """Queue ``steps`` dense on-demand capture windows (the
        ``/profile?steps=N`` endpoint); thread-safe. The TOTAL pending is
        clamped to ``MAX_PENDING_CAPTURE``. Returns the total now
        pending."""
        steps = max(int(steps), 1)
        with self._lock:
            self._pending = min(self._pending + steps, MAX_PENDING_CAPTURE)
            return self._pending

    # -- windows -------------------------------------------------------------

    def _open_window(self):
        t0 = self._clock()
        with self._lock:
            on_demand = self._pending > 0
            if on_demand:
                self._pending -= 1
            self._window_trigger = "on_demand" if on_demand else "cadence"
            self._window = {}
            self._bytes_open = self._probe_bytes()
            self.active = True
            self._window_t0 = self._clock()
        self._open_cost = self._clock() - t0

    def _close_window(self, now) -> bool:
        """Fold the open window (call with ``self._lock`` held — on_step
        does); returns True when the caller should reconcile."""
        window_wall = now - (self._window_t0 or now)
        t0 = self._clock()
        programs_s = 0.0
        with self._lock:
            self.active = False
            window, self._window = self._window, {}
            self.windows += 1
            trigger = self._window_trigger
            bytes_close = self._probe_bytes()
            if bytes_close is not None and self._bytes_open is not None:
                delta = bytes_close - self._bytes_open
                self.hbm_delta_bytes = delta if self.hbm_delta_bytes is None \
                    else int(0.5 * self.hbm_delta_bytes + 0.5 * delta)
            for name, (calls, secs) in window.items():
                programs_s += secs
                st = self._programs.setdefault(
                    name, {"ms": None, "calls": 0, "windows": 0})
                ms = secs * 1e3
                st["ms"] = ms if st["ms"] is None \
                    else 0.5 * st["ms"] + 0.5 * ms
                st["calls"] += calls
                st["windows"] += 1
            self._account_overhead(window_wall, programs_s,
                                   self._clock() - t0, trigger)
            want_reconcile = (
                self.auto_reconcile and
                self.windows >= self.RECONCILE_AFTER_WINDOWS and (
                    self._reconciled_at == 0 or
                    self.windows - self._reconciled_at >=
                    self.RECONCILE_REFRESH_WINDOWS))
            if want_reconcile:
                self._reconciled_at = self.windows
        self._c_windows.inc(trigger=trigger)
        return want_reconcile

    def _account_overhead(self, window_wall, programs_s, close_cost,
                          trigger):
        """Fold one window's measured cost into the overhead EWMA and back
        the cadence off past the budget. On-demand windows are exempt —
        the operator asked for them.

        The cost model is pipeline-aware: in a loop that only enqueues,
        unprofiled steps measure host dispatch (milliseconds) while the
        profiled step's block surfaces the device work that was
        overlapping — wall minus steady EWMA would bill the sampler for
        compute the device owed anyway. So the step's true cost floor is
        ``max(steady EWMA, the window's own measured program seconds)``;
        only wall time BEYOND that floor (plus direct bookkeeping — the
        HBM probes) is sampler overhead, amortized over the cadence."""
        if trigger != "cadence" or self.steady_step_s is None \
                or self.steady_step_s <= 0:
            return
        step_cost = max(self.steady_step_s, programs_s)
        excess = max(window_wall - step_cost, 0.0)
        cost = excess + close_cost + self._open_cost
        pct = cost / (self.every * step_cost) * 100.0
        self.overhead_pct = pct if self.windows <= 1 \
            else 0.5 * self.overhead_pct + 0.5 * pct
        self._g_overhead.set(round(self.overhead_pct, 4))
        if self.overhead_pct > self.budget_pct and self.every < MAX_EVERY:
            self.every = min(self.every * 2, MAX_EVERY)
            self._g_every.set(self.every)
            self._c_backoffs.inc()

    def _probe_bytes(self):
        if not self.memory_probe:
            return None
        try:
            from .. import memory as _memory
            return int(_memory.current_bytes())
        except Exception:
            return None

    # -- hook-side recording -------------------------------------------------

    def record(self, name: str, seconds: float) -> None:
        """One dispatched program's wall time inside the open window
        (called by the jit/optimizer/prefetch/collective hooks)."""
        if not self.active:
            return
        with self._lock:
            if not self.active:
                return  # the window closed while we raced for the lock
            row = self._window.get(name)
            if row is None:
                row = self._window[name] = [0, 0.0]
            row[0] += 1
            row[1] += seconds
        self._h_program.observe(seconds * 1e3, program=name)

    def note_program(self, name: str, obj) -> None:
        """Remember (weakly) the StaticFunction behind a profiled program
        so reconciliation can re-analyze its jaxpr later."""
        try:
            ref = weakref.ref(obj)
        except TypeError:
            return
        with self._lock:
            self._static_fns[name] = ref

    def static_fn(self, name: str):
        ref = self._static_fns.get(name)
        return ref() if ref is not None else None

    # -- reads ---------------------------------------------------------------

    def program_stats(self) -> dict:
        """{program: {"ms_per_step", "calls", "windows", "share"}} —
        EWMA wall ms per profiled step, per program."""
        with self._lock:
            progs = {k: dict(v) for k, v in self._programs.items()}
        total = sum(v["ms"] or 0.0 for v in progs.values()) or 1.0
        return {k: {"ms_per_step": round(v["ms"] or 0.0, 3),
                    "calls": v["calls"], "windows": v["windows"],
                    "share": round((v["ms"] or 0.0) / total, 4)}
                for k, v in progs.items()}

    def steps_per_sec(self, window: float = 30.0) -> float:
        return self._c_steps.rate(window)

    def snapshot(self) -> dict:
        """JSON-safe self-description (flight dumps, /healthz, bench)."""
        return {
            "every": self.every,
            "base_every": self.base_every,
            "budget_pct": self.budget_pct,
            "overhead_pct": round(self.overhead_pct, 4),
            "windows": self.windows,
            "steps_seen": self._count,
            "steady_step_ms": round(self.steady_step_s * 1e3, 3)
            if self.steady_step_s else None,
            "hbm_delta_bytes": self.hbm_delta_bytes,
            "programs": self.program_stats(),
        }

    def reset(self, every: int | None = None) -> None:
        """Forget windows/EWMAs/programs (bench sections, tests); the
        cadence returns to ``every`` or its configured base."""
        with self._lock:
            self.active = False
            self._pending = 0
            self._count = 0
            self._last_t = None
            self._window = {}
            self._window_t0 = None
            self.steady_step_s = None
            self.overhead_pct = 0.0
            self.windows = 0
            self.hbm_delta_bytes = None
            self._programs.clear()
            self._static_fns.clear()
            self._reconciled_at = 0
            self.every = max(every, 1) if every is not None \
                else self.base_every
            self._g_every.set(self.every)
            self._g_overhead.set(0.0)


# ---------------------------------------------------------------------------
# process-wide default profiler + module-level API (the hot-site surface)
# ---------------------------------------------------------------------------

_default: ContinuousProfiler | None = None
_default_lock = threading.Lock()


def get_profiler() -> ContinuousProfiler:
    """The process-wide profiler every framework hook records into
    (created on first use)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = ContinuousProfiler()
    return _default


def profiler_if_started() -> ContinuousProfiler | None:
    """The default profiler ONLY if something already created it — the
    read-side accessor (/healthz, flight dumps) that must not spin up
    sampling machinery in processes that never profile."""
    return _default


def sampling_active() -> bool:
    """True while a capture window is open — the one test every dispatch
    hook pays per call (an attribute read; no profiler is even created)."""
    p = _default
    return p is not None and p.active


def record_program(name: str, seconds: float) -> None:
    p = _default
    if p is not None and p.active:
        p.record(name, seconds)


def note_program(name: str, obj) -> None:
    p = _default
    if p is not None and p.active:
        p.note_program(name, obj)


def on_step(step: int | None = None) -> None:
    """Drive the default profiler: call once per training step."""
    get_profiler().on_step(step)


def stop() -> None:
    p = _default
    if p is not None:
        p.stop()


def reset(every: int | None = None) -> None:
    get_profiler().reset(every=every)


def profile_snapshot() -> dict | None:
    """The default profiler's snapshot + last reconciliation, or None when
    nothing ever profiled (flight dumps embed this)."""
    p = _default
    if p is None or (p.windows == 0 and p._count == 0):
        return None
    snap = p.snapshot()
    from .reconcile import last_reconciliation
    targets = last_reconciliation()
    if targets is not None:
        snap["fusion_targets"] = targets
    return snap


# reconciliation + server: re-exported here so the public surface is one
# module (paddle.observability.continuous.*; serve also rides
# paddle.observability.serve)
from .reconcile import (fusion_targets, last_reconciliation,  # noqa: E402,F401
                        last_unfused_reconciliation)
from .server import TelemetryServer, serve, shutdown_server  # noqa: E402,F401
