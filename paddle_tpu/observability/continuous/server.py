"""Live telemetry HTTP server: the scrape surface a serving runtime and
multi-host training stand on.

Zero-dependency (stdlib ``http.server``, threaded, daemonic) so it can run
inside every training/serving process. Endpoints:

* ``GET /metrics`` — the existing Prometheus text exposition
  (``observability.exporters.render_prometheus``), content type
  ``text/plain; version=0.0.4``.
* ``GET /healthz`` — step liveness as JSON: 200 while the last
  ``continuous.on_step`` is younger than the stall threshold
  (``PADDLE_TPU_HEALTH_STALL_S``, default 120s), **503** when steps have
  stalled, 200 ``{"status": "idle"}`` before any step. Carries
  ``steps_per_s`` from the registry's windowed rate — no scrape-side math.
* ``GET /flight`` — the flight recorder's current ring buffer as strict
  RFC-8259 JSON (NaN losses stringified, same sanitizer as dumps), plus
  the profiler snapshot when one exists.
* ``GET /profile?steps=N`` — queue N dense on-demand capture windows on
  the continuous profiler (the next N training steps are profiled).
* ``GET /requests?last=N`` — the request tracer's ring of completed
  serving requests (lifecycle timing breakdown per record) plus the
  TTFT/TPOT histogram exemplars (bucket → trace id).
* ``GET /trace/<trace_id>`` — one request's span tree (completed
  reservoir or still in flight); 404 on an unknown id.

Start with ``paddle_tpu.observability.serve(port)`` (env:
``PADDLE_TPU_METRICS_PORT``; port 0 binds an ephemeral port — tests). The
server shuts down cleanly via ``close()``; the preemption handler calls
:func:`shutdown_server` during its drain so a preempted process leaves no
dangling acceptor thread.
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ...analysis.concurrency import tsan as _tsan

__all__ = ["TelemetryServer", "serve", "shutdown_server",
           "register_route", "unregister_route",
           "register_health_provider",
           "DEFAULT_PORT", "DEFAULT_STALL_S"]

DEFAULT_PORT = 9406
DEFAULT_STALL_S = 120.0
#: /profile?steps=N per-request ceiling: every on-demand window makes the
#: NEXT step's dispatches block on device results (budget-exempt), so an
#: unauthenticated peer must not be able to queue an unbounded slowdown
#: (the profiler also clamps TOTAL pending to its MAX_PENDING_CAPTURE)
MAX_PROFILE_STEPS = 1000


def _env_port() -> int:
    try:
        return int(os.environ.get("PADDLE_TPU_METRICS_PORT", DEFAULT_PORT))
    except ValueError:
        return DEFAULT_PORT


def _env_stall() -> float:
    try:
        return float(os.environ.get("PADDLE_TPU_HEALTH_STALL_S",
                                    DEFAULT_STALL_S))
    except ValueError:
        return DEFAULT_STALL_S


# -- extension points (the serving runtime mounts itself here) ---------------
#
# Routes: path -> fn(handler, method, query, body_bytes). The fn owns the
# whole response (handler._send / _send_json / raw writes for streaming).
# Health: provider(stall_after_s) -> (code, payload) | None; a non-None
# return REPLACES the training-step liveness payload — this is how
# /healthz learns serving mode (decode-step staleness) when an engine is
# attached, without the server knowing what serving is.

_EXTRA_ROUTES: dict = {}
_HEALTH_PROVIDER = None
# registration is copy-on-write under this lock: handler threads read
# _EXTRA_ROUTES bare (one atomic load of an immutable-once-published
# dict), so a serving runtime mounting itself mid-scrape can never make
# a handler iterate a dict that changes size under it
_ext_lock = _tsan.lock("observability.continuous.server.ext")


def register_route(path: str, fn) -> None:
    """Mount ``fn(handler, method, query, body)`` at ``path`` on every
    (current and future) telemetry server in this process."""
    global _EXTRA_ROUTES
    with _ext_lock:
        routes = dict(_EXTRA_ROUTES)
        routes[path] = fn
        _EXTRA_ROUTES = routes


def unregister_route(path: str) -> None:
    global _EXTRA_ROUTES
    with _ext_lock:
        routes = dict(_EXTRA_ROUTES)
        routes.pop(path, None)
        _EXTRA_ROUTES = routes


def register_health_provider(fn) -> None:
    """Install (or clear, with None) the /healthz override provider."""
    global _HEALTH_PROVIDER
    with _ext_lock:
        _HEALTH_PROVIDER = fn


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-telemetry/1"

    def log_message(self, *args):   # stdout silence: this runs inside
        pass                        # training processes

    # -- plumbing ------------------------------------------------------------

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload: dict):
        # same sanitizers as flight dumps: _finite stringifies NaN/Inf,
        # _json_safe catches non-native field values (np scalars, Paths)
        # recorded through flight.record's open **fields API
        from ..flight import _finite, _json_safe
        self._send(code, json.dumps(_finite(payload),
                                    default=_json_safe).encode(),
                   "application/json")

    # -- routes --------------------------------------------------------------

    def _dispatch(self, method: str, body: bytes | None):
        try:
            url = urlparse(self.path)
            routes_snapshot = _EXTRA_ROUTES   # one load; never mutated
            extra = routes_snapshot.get(url.path)
            if extra is not None:
                extra(self, method, parse_qs(url.query), body)
                return
            route = {"/metrics": self._metrics, "/healthz": self._healthz,
                     "/flight": self._flight, "/profile": self._profile,
                     "/requests": self._requests,
                     "/dashboard": self._dashboard}.get(url.path)
            if route is None and url.path.startswith("/trace/"):
                if method != "GET":
                    self._send_json(405, {
                        "error": f"no {method} route {url.path!r}"})
                    return
                self._trace(url.path[len("/trace/"):], parse_qs(url.query))
                return
            if route is None or method != "GET":
                self._send_json(404 if route is None else 405, {
                    "error": f"no {method} route {url.path!r}",
                    "routes": sorted(["/metrics", "/healthz", "/flight",
                                      "/profile", "/requests", "/dashboard",
                                      "/trace/<trace_id>"] +
                                     list(routes_snapshot))})
                return
            route(parse_qs(url.query))
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # a scrape must never kill the process
            try:
                self._send_json(500, {"error": repr(e)[:300]})
            except Exception:
                pass

    def do_GET(self):  # noqa: N802 (http.server contract)
        self._dispatch("GET", None)

    def do_POST(self):  # noqa: N802 (serving's /generate arrives here)
        try:
            # clamp below too: a negative Content-Length would turn
            # read() into read-until-EOF and pin this handler thread
            n = max(0, int(self.headers.get("Content-Length") or 0))
        except ValueError:
            n = 0
        body = self.rfile.read(min(n, 16 * 1024 * 1024)) if n else b""
        self._dispatch("POST", body)

    def _metrics(self, _q):
        from ..exporters import render_prometheus
        self._send(200, render_prometheus().encode(),
                   "text/plain; version=0.0.4; charset=utf-8")

    def _dashboard(self, _q):
        # zero-dep HTML view: inline SVG sparklines over the active
        # HealthMonitor's window history + live ledger (health tier)
        from ..health import dashboard as _hd
        self._send(200, _hd.render_dashboard().encode("utf-8"),
                   "text/html; charset=utf-8")

    def _healthz(self, _q):
        import time
        from . import profiler_if_started
        stall = self.server.stall_after_s  # type: ignore[attr-defined]
        provider = _HEALTH_PROVIDER        # one load vs register races
        if provider is not None:
            override = provider(stall)
            if override is not None:
                code, payload = override
                self._send_json(code, payload)
                return
        p = profiler_if_started()
        if p is None or p.last_step_wall is None:
            self._send_json(200, {"status": "idle", "last_step": None,
                                  "stall_after_s": stall})
            return
        age = time.time() - p.last_step_wall
        payload = {
            "status": "ok" if age <= stall else "stalled",
            "last_step": p.last_step,
            "last_step_age_s": round(age, 3),
            "stall_after_s": stall,
            "steps_per_s": round(p.steps_per_sec(), 4),
            "prof_overhead_pct": round(p.overhead_pct, 4),
        }
        self._send_json(200 if age <= stall else 503, payload)

    def _flight(self, _q):
        from .. import flight
        from . import profile_snapshot
        rec = flight.get_recorder()
        payload = {"enabled": rec.enabled, "capacity": rec.capacity,
                   "events": rec.events()}
        snap = profile_snapshot()
        if snap is not None:
            payload["profile"] = snap
        self._send_json(200, payload)

    def _requests(self, q):
        """Recent completed requests: the tracer's request-log ring plus
        histogram exemplars (the trace-id join for TTFT/TPOT buckets)."""
        from .. import tracing
        try:
            last = int(q.get("last", ["50"])[0])
        except ValueError:
            self._send_json(400, {"error": "last must be an int"})
            return
        tr = tracing.get_tracer()
        self._send_json(200, {"enabled": tr.enabled,
                              "requests": tr.requests(last),
                              "exemplars": tr.exemplars(),
                              "stats": tr.stats()})

    def _trace(self, trace_id, _q):
        """Span tree of one trace (completed reservoir or in-flight)."""
        from .. import tracing
        snap = tracing.get_trace(trace_id)
        if snap is None:
            self._send_json(404, {"error": f"unknown trace id "
                                           f"{trace_id!r}"})
            return
        self._send_json(200, snap)

    def _profile(self, q):
        from . import get_profiler
        try:
            steps = int(q.get("steps", ["1"])[0])
        except ValueError:
            self._send_json(400, {"error": "steps must be an int"})
            return
        if steps < 1 or steps > MAX_PROFILE_STEPS:
            self._send_json(400, {"error": f"steps must be in "
                                           f"[1, {MAX_PROFILE_STEPS}]"})
            return
        p = get_profiler()
        if not p.enabled:
            # on_step() never consumes pending windows when the sampler is
            # off — queuing them would be a silent no-op the caller reads
            # as "capture armed"
            self._send_json(409, {"error": "continuous profiler is "
                                           "disabled (PADDLE_TPU_PROF=0)"})
            return
        pending = p.request_capture(steps)
        self._send_json(200, {"requested": steps, "pending": pending,
                              "active": p.active, "every": p.every})


class TelemetryServer:
    """Threaded HTTP server over the process's telemetry. Construct via
    :func:`serve` (module-tracked, drain-aware) or directly for tests::

        srv = TelemetryServer(port=0).start()   # ephemeral port
        ...
        srv.close()                             # joins the acceptor thread
    """

    def __init__(self, port: int | None = None, host: str | None = None,
                 stall_after_s: float | None = None):
        port = _env_port() if port is None else int(port)
        if host is None:
            # scrape surfaces conventionally bind all interfaces, but the
            # endpoints are unauthenticated (/flight leaks run internals,
            # /profile costs step time) — PADDLE_TPU_METRICS_HOST=127.0.0.1
            # confines them to the host on untrusted networks
            host = os.environ.get("PADDLE_TPU_METRICS_HOST", "0.0.0.0")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.stall_after_s = (  # type: ignore[attr-defined]
            _env_stall() if stall_after_s is None else float(stall_after_s))
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"paddle-tpu-telemetry:{self.port}", daemon=True)

    def start(self) -> "TelemetryServer":
        # materialize the profiler so /metrics exposes the full continuous
        # schema (HELP/TYPE of the program histograms) from the first scrape
        from . import get_profiler
        get_profiler()
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._thread.is_alive()

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting, close the socket, join the acceptor thread —
        BOUNDED by ``timeout``, with a loud RuntimeWarning if the
        acceptor refuses to die (a wedged handler must not turn process
        shutdown into a hang). Idempotent; safe from any thread,
        including on a server that was constructed but never started
        (shutdown() would block forever waiting on an Event only
        serve_forever sets)."""
        try:
            if self._thread.is_alive():
                self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout)
            if self._thread.is_alive():
                warnings.warn(
                    f"telemetry server acceptor thread "
                    f"{self._thread.name!r} did not exit within "
                    f"{timeout}s of close()", RuntimeWarning,
                    stacklevel=2)

    def __enter__(self) -> "TelemetryServer":
        return self if self.running else self.start()

    def __exit__(self, *exc):
        self.close()
        return False


_server: TelemetryServer | None = None
_server_lock = threading.Lock()


def serve(port: int | None = None, host: str | None = None,
          stall_after_s: float | None = None) -> TelemetryServer:
    """Start (or replace) the process-wide telemetry server and return it.
    ``port=None`` reads ``PADDLE_TPU_METRICS_PORT`` (default 9406);
    ``port=0`` binds an ephemeral port (``.port`` says which). The
    preemption drain shuts this server down via :func:`shutdown_server`."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.close()
        _server = TelemetryServer(port=port, host=host,
                                  stall_after_s=stall_after_s).start()
        return _server


def shutdown_server(timeout: float = 5.0) -> bool:
    """Close the process-wide server if one is running (idempotent).
    Returns True when a server was actually shut down."""
    global _server
    with _server_lock:
        if _server is None:
            return False
        _server.close(timeout)
        _server = None
        return True
