"""Static->measured reconciliation: the ranked mega-kernel work queue.

PR 6's graph analyzer ranks fusion candidates by *estimated* saved HBM
bytes; the continuous profiler measures where step time *actually* goes.
This module joins the two: for every profiled ``to_static`` program it
re-runs the graph analyzer on the program's cached jaxpr
(``StaticFunction.analyze_cached`` — an abstract trace, no device
execution) and calls :func:`paddle_tpu.analysis.graph.join_measured` to
attribute the program's measured ms/step to each GA100 candidate by its
share of the program's HBM traffic (the right prior for memory-bound
programs — rule GA109's model). The result is the ``fusion_targets``
table: candidate name, sites, estimated saved bytes, **measured** ms/step
share — bench.py embeds it (``extra.fusion_targets``), the report CLI
renders it, and flight dumps carry the last computed copy.
"""

from __future__ import annotations

import threading

__all__ = ["fusion_targets", "last_reconciliation",
           "last_unfused_reconciliation", "render_targets"]

_last_lock = threading.Lock()
_last: list | None = None
_last_unfused: list | None = None

#: serializes the dispatch-global flips of the as-fused/composite views
_view_lock = threading.Lock()


def last_reconciliation() -> list | None:
    """The most recently computed fusion-target table (None before the
    first reconciliation). Flight dumps embed this instead of re-running
    the analyzer in a dying process."""
    with _last_lock:
        return None if _last is None else [dict(t) for t in _last]


def last_unfused_reconciliation() -> list | None:
    """The composite-view table from the most recent reconciliation that
    computed one (``fusion_targets(with_unfused=True)``) — the 'before'
    side of the harvested-delta pair bench.py embeds."""
    with _last_lock:
        return None if _last_unfused is None \
            else [dict(t) for t in _last_unfused]


def _set_last(targets: list, unfused: list | None = None) -> None:
    global _last, _last_unfused
    with _last_lock:
        _last = [dict(t) for t in targets]
        if unfused is not None:
            _last_unfused = [dict(t) for t in unfused]


def _view_report(sf, view: str):
    """Analyze one profiled program as it compiles in a given world.

    ``view="fused"``: the TPU program — every Pallas kernel (incl. the
    block mega-kernels) dispatched. On a host without the kernels
    (the CPU-smoke bench) this force-dispatches during an abstract
    re-trace only; nothing is executed, exactly the
    ``_common.force_dispatch`` lowering-trace contract. Candidates whose
    region is a block kernel come back ``fused: true``.

    ``view="unfused"``: the pure-XLA composite (kernels flagged off) —
    the 'before' side showing what fusion still claims.

    The re-trace runs the model's Python forward again, so: the module
    lock serializes the brief dispatch-global flips (the continuous
    profiler reconciles from the training thread between steps — a
    concurrent OTHER thread executing model code inside the window would
    see the flipped flags, so reconcile from the step loop, not a side
    thread), the framework RNG state is snapshotted and restored (a
    trace-time ``default_generator.split()`` in a dropout seed path must
    not advance the run's RNG stream just because telemetry looked), and
    any failure (a kernel wrapper rejecting the re-traced shapes, a
    stale cache) falls back to the program's default cached report.
    """
    from ...analysis.graph.rules import GraphRuleConfig
    from ...core import generator as gen_mod
    from ...core.flags import flag, set_flags
    from ...ops.kernels import _common as kern

    def _fresh():
        rng_state = gen_mod.default_generator.get_state()
        try:
            return sf.analyze_cached(config=GraphRuleConfig.from_env(),
                                     fresh=True)
        finally:
            gen_mod.default_generator.set_state(rng_state)

    try:
        with _view_lock:
            if view == "fused" and not kern.available():
                kern.force_dispatch(True)
                try:
                    return _fresh()
                finally:
                    kern.force_dispatch(False)
            if view == "unfused" and flag("use_pallas_kernels"):
                set_flags({"use_pallas_kernels": 0})
                try:
                    return _fresh()
                finally:
                    set_flags({"use_pallas_kernels": 1})
            if view == "unfused":
                return _fresh()
            return sf.analyze_cached()
    except Exception:
        try:
            return sf.analyze_cached()
        except Exception:
            return None


def fusion_targets(top: int = 10, profiler=None,
                   with_unfused: bool = False) -> list:
    """Reconcile measured per-program time with static GA100 candidates.

    Returns up to ``top`` remaining-opportunity rows PLUS every harvested
    (``fused``) row — the table must show where the measured share went,
    so fused rows are exempt from the cap — sorted by
    ``measured_ms_share`` descending (ties broken by
    ``est_saved_bytes``), each::

        {"name", "sites", "n_ops", "span", "program",
         "est_saved_bytes",          # static, per site
         "est_saved_bytes_total",    # static, x sites
         "measured_ms",              # the program's measured ms/step
         "measured_ms_share",        # attributed to this candidate
         "fused",                    # region already a block mega-kernel
         "measured_hbm_delta_bytes"} # window HBM delta (when probed)

    The table reflects the program AS IT COMPILES WITH THE KERNELS ON
    (the as-fused view — on a CPU-smoke host the candidates come from a
    force-dispatch abstract re-trace, see :func:`_view_report`): rows
    covered by a ``block_*_epilogue`` mega-kernel carry ``fused: true``
    with their attributed share, and the un-fused rows are the REMAINING
    opportunity ranking. ``with_unfused=True`` additionally computes the
    composite 'before' view (``last_unfused_reconciliation``) so callers
    (bench.py) can embed the harvested delta.

    Programs without an analyzable jaxpr (the fused optimizer dispatch,
    prefetch/collective waits) contribute measured time but no candidates
    and are skipped. Never raises past its guard: an analysis failure on
    one program drops that program, not the table.
    """
    from . import get_profiler
    p = profiler or get_profiler()
    stats = p.program_stats()
    targets: list = []
    unfused_targets: list = []
    from ...analysis.graph import join_measured
    for name, st in stats.items():
        sf = p.static_fn(name)
        if sf is None or not hasattr(sf, "analyze_cached"):
            continue
        report = _view_report(sf, "fused")
        if report is not None:
            targets.extend(join_measured(
                report, measured_ms=st["ms_per_step"], program=name,
                hbm_delta_bytes=p.hbm_delta_bytes))
        if with_unfused:
            before = _view_report(sf, "unfused")
            if before is not None:
                unfused_targets.extend(join_measured(
                    before, measured_ms=st["ms_per_step"], program=name,
                    hbm_delta_bytes=p.hbm_delta_bytes))

    def _rank(rows):
        rows.sort(key=lambda t: (-t["measured_ms_share"],
                                 -t["est_saved_bytes"], t["name"]))
        # harvested (fused) rows always stay visible: the table must show
        # WHERE the measured share went, not only what remains — `top`
        # bounds the remaining-opportunity rows
        fused_rows = [t for t in rows if t.get("fused")]
        remaining = [t for t in rows if not t.get("fused")][:top]
        out = sorted(fused_rows + remaining,
                     key=lambda t: (-t["measured_ms_share"],
                                    -t["est_saved_bytes"], t["name"]))
        return out

    targets = _rank(targets)
    _set_last(targets, _rank(unfused_targets) if with_unfused else None)
    return targets


def render_targets(targets: list, overhead_pct=None) -> str:
    """Human table of a fusion-target list (the report CLI's output)."""
    out = ["rank  candidate                 sites  est saved/site  "
           "measured ms/step  program"]
    for i, t in enumerate(targets, 1):
        # .get defaults: --from-bench rows come from arbitrary (older,
        # hand-edited) bench lines, not just our own join_measured output
        mib = t.get("est_saved_bytes", 0) / (1 << 20)
        name = t.get("name", "?")
        if t.get("fused"):
            name += " [fused]"
        out.append(f"{i:<5} {name:<25} "
                   f"{t.get('sites', 1):>5}  {mib:>10.2f} MiB  "
                   f"{t.get('measured_ms_share', 0.0):>16.3f}  "
                   f"{t.get('program', '')}")
    if not targets:
        out.append("(no reconciled candidates — profile a to_static "
                   "program first)")
    if overhead_pct is not None:
        out.append(f"sampler overhead: {overhead_pct:.3f}% of steady-state "
                   f"step time")
    return "\n".join(out)
