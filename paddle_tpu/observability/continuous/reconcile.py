"""Static->measured reconciliation: the ranked mega-kernel work queue.

PR 6's graph analyzer ranks fusion candidates by *estimated* saved HBM
bytes; the continuous profiler measures where step time *actually* goes.
This module joins the two: for every profiled ``to_static`` program it
re-runs the graph analyzer on the program's cached jaxpr
(``StaticFunction.analyze_cached`` — an abstract trace, no device
execution) and calls :func:`paddle_tpu.analysis.graph.join_measured` to
attribute the program's measured ms/step to each GA100 candidate by its
share of the program's HBM traffic (the right prior for memory-bound
programs — rule GA109's model). The result is the ``fusion_targets``
table: candidate name, sites, estimated saved bytes, **measured** ms/step
share — bench.py embeds it (``extra.fusion_targets``), the report CLI
renders it, and flight dumps carry the last computed copy.
"""

from __future__ import annotations

import threading

__all__ = ["fusion_targets", "last_reconciliation", "render_targets"]

_last_lock = threading.Lock()
_last: list | None = None


def last_reconciliation() -> list | None:
    """The most recently computed fusion-target table (None before the
    first reconciliation). Flight dumps embed this instead of re-running
    the analyzer in a dying process."""
    with _last_lock:
        return None if _last is None else [dict(t) for t in _last]


def _set_last(targets: list) -> None:
    global _last
    with _last_lock:
        _last = [dict(t) for t in targets]


def fusion_targets(top: int = 10, profiler=None) -> list:
    """Reconcile measured per-program time with static GA100 candidates.

    Returns up to ``top`` rows sorted by ``measured_ms_share`` descending
    (ties broken by ``est_saved_bytes``), each::

        {"name", "sites", "n_ops", "span", "program",
         "est_saved_bytes",          # static, per site
         "est_saved_bytes_total",    # static, x sites
         "measured_ms",              # the program's measured ms/step
         "measured_ms_share",        # attributed to this candidate
         "measured_hbm_delta_bytes"} # window HBM delta (when probed)

    Programs without an analyzable jaxpr (the fused optimizer dispatch,
    prefetch/collective waits) contribute measured time but no candidates
    and are skipped. Never raises past its guard: an analysis failure on
    one program drops that program, not the table.
    """
    from . import get_profiler
    p = profiler or get_profiler()
    stats = p.program_stats()
    targets: list = []
    for name, st in stats.items():
        sf = p.static_fn(name)
        if sf is None or not hasattr(sf, "analyze_cached"):
            continue
        try:
            report = sf.analyze_cached()
        except Exception:
            report = None
        if report is None:
            continue
        from ...analysis.graph import join_measured
        targets.extend(join_measured(
            report, measured_ms=st["ms_per_step"], program=name,
            hbm_delta_bytes=p.hbm_delta_bytes))
    targets.sort(key=lambda t: (-t["measured_ms_share"],
                                -t["est_saved_bytes"], t["name"]))
    targets = targets[:top]
    _set_last(targets)
    return targets


def render_targets(targets: list, overhead_pct=None) -> str:
    """Human table of a fusion-target list (the report CLI's output)."""
    out = ["rank  candidate                 sites  est saved/site  "
           "measured ms/step  program"]
    for i, t in enumerate(targets, 1):
        # .get defaults: --from-bench rows come from arbitrary (older,
        # hand-edited) bench lines, not just our own join_measured output
        mib = t.get("est_saved_bytes", 0) / (1 << 20)
        out.append(f"{i:<5} {t.get('name', '?'):<25} "
                   f"{t.get('sites', 1):>5}  {mib:>10.2f} MiB  "
                   f"{t.get('measured_ms_share', 0.0):>16.3f}  "
                   f"{t.get('program', '')}")
    if not targets:
        out.append("(no reconciled candidates — profile a to_static "
                   "program first)")
    if overhead_pct is not None:
        out.append(f"sampler overhead: {overhead_pct:.3f}% of steady-state "
                   f"step time")
    return "\n".join(out)
