"""``python -m paddle_tpu.observability.continuous report`` — render the
reconciled fusion-target table (the measured mega-kernel work queue).

Two sources:

* ``--from-bench BENCH.json`` — read an existing bench line's
  ``extra.fusion_targets`` (and ``telemetry.prof_overhead_pct``) and
  render it; no device work.
* default (live) — run a small profiled CPU training loop over the tiny
  GPT (``--steps``, profiler cadence ``--every``), reconcile, and render.
  This is the zero-to-table path: it exercises the exact sampler +
  reconciliation machinery a real run wires in via ``on_step``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _live_targets(steps: int, every: int, top: int):
    """Profile a tiny GPT train loop on CPU and reconcile."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.observability import continuous as cont

    paddle.seed(0)
    vocab, seq, batch = 512, 64, 8
    model = GPT(GPTConfig(vocab_size=vocab, max_position_embeddings=seq,
                          hidden_size=128, num_layers=2, num_heads=4))
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq + 1))
    x = paddle.to_tensor(ids[:, :-1].astype(np.int32))
    y = paddle.to_tensor(ids[:, 1:].astype(np.int32))

    @paddle.jit.to_static
    def step(x, y):
        _, loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    prof = cont.get_profiler()
    prof.reset(every=every)
    prof.auto_reconcile = False   # reconcile once, explicitly, below
    for i in range(steps):
        step(x, y)
        cont.on_step(i)
    cont.stop()
    return cont.fusion_targets(top=top), prof.overhead_pct


def _bench_targets(path: str):
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))))
    from tools.perf_gate import load_bench
    d = load_bench(path)
    targets = (d.get("extra") or {}).get("fusion_targets") or []
    tel = d.get("telemetry")
    overhead = tel.get("prof_overhead_pct") if isinstance(tel, dict) \
        else None
    return ([t for t in targets if isinstance(t, dict)], overhead)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.continuous",
        description="Continuous-profiler tooling: measured fusion-target "
                    "reconciliation report.")
    sub = ap.add_subparsers(dest="cmd")
    rep = sub.add_parser(
        "report", help="render the ranked fusion-target table")
    rep.add_argument("--from-bench", metavar="BENCH_JSON",
                     help="read extra.fusion_targets from a bench line "
                          "instead of running a live profiled loop")
    rep.add_argument("--steps", type=int, default=8,
                     help="live mode: profiled train steps (default 8)")
    rep.add_argument("--every", type=int, default=2,
                     help="live mode: profiler cadence (default 2)")
    rep.add_argument("--top", type=int, default=10)
    rep.add_argument("--json", action="store_true",
                     help="print the raw target list as JSON")
    args = ap.parse_args(argv)
    if args.cmd != "report":
        ap.print_help()
        return 2
    from .reconcile import render_targets
    if args.from_bench:
        try:
            targets, overhead = _bench_targets(args.from_bench)
        except (OSError, ValueError) as e:
            print(f"cannot read bench file {args.from_bench!r}: {e}",
                  file=sys.stderr)
            return 1
        targets = targets[:args.top]
    else:
        targets, overhead = _live_targets(args.steps, args.every, args.top)
    if args.json:
        print(json.dumps({"fusion_targets": targets,
                          "prof_overhead_pct": overhead}))
    else:
        print(render_targets(targets, overhead_pct=overhead))
    return 0


if __name__ == "__main__":
    sys.exit(main())
