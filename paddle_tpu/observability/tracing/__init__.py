"""Per-request distributed tracing for the serving path (ISSUE 16).

The metrics registry, flight recorder and continuous profiler are all
step- and program-centric; this package adds the request axis: every
``LLMEngine.submit`` opens a **root span** carrying a 128-bit trace id,
and the scheduler emits **child spans** for each lifecycle stage (queue
wait, admission, prefill chunks, burst-aggregated decode/speculate
iterations, eviction, COW copies, stream emission). A p99 TTFT outlier
becomes explainable: its histogram exemplar names a trace id, and
``GET /trace/<id>`` returns the span tree that says where the time went.

Design rules (shared with the rest of the observability stack):

* **zero dependencies** — stdlib only;
* **type-identity no-op when off** — ``PADDLE_TPU_TRACE=0`` makes
  :func:`start_request` return the module-level :data:`NOOP_TRACE`
  singleton whose methods return :data:`NOOP_SPAN`; hot call sites guard
  with an identity check (``trace is NOOP_TRACE``) so the disabled cost
  is one pointer comparison;
* **measured overhead** — the tracer self-times its span-append path
  (``stats()["cost_s"]``); ``bench.py serve`` folds that into
  ``extra.serve.tracing.overhead_pct`` and ``tools/perf_gate.py``
  soft-gates it (``PERF_GATE_TRACE_TOL_PCT``, default 1%);
* **bounded everywhere** — per-request span buffer
  (``PADDLE_TPU_TRACE_SPANS``), completed-trace reservoir
  (``PADDLE_TPU_TRACE_RESERVOIR``), request-log ring
  (``PADDLE_TPU_TRACE_REQUESTS``) and the live-trace table all evict
  oldest-first; nothing grows without bound on a leaked request;
* **leaf locks** — the tracer's locks are leaves: no code path calls
  back into the scheduler, pool or metrics registry while holding one,
  and the :class:`Tracer` lock and a :class:`RequestTrace` lock are
  never held at the same time (no edges for the lock-order analyzer).

Context propagation uses the W3C ``traceparent`` wire format
(``00-<32 hex trace id>-<16 hex parent span id>-<2 hex flags>``) so a
future fleet router can carry a request across prefill/decode pools;
malformed values are rejected (→ fresh trace), never fail the request.

``python -m paddle_tpu.observability.tracing <flight_dump.json>
--chrome-trace out.json`` renders the spans a dying process carried in
its flight dump — open spans become ``ph:"B"`` begin events, the same
unmatched-span convention the flight exporter uses for death spans.
"""

from __future__ import annotations

import json
import os
import time
from collections import OrderedDict, deque

from ...analysis.concurrency import tsan as _tsan

__all__ = [
    "TraceContext",
    "parse_traceparent",
    "Span",
    "RequestTrace",
    "Tracer",
    "get_tracer",
    "tracing_enabled",
    "enable",
    "start_request",
    "get_trace",
    "requests",
    "open_spans",
    "note_exemplar",
    "exemplars",
    "flight_snapshot",
    "to_chrome_trace",
    "render_request_log",
    "stats",
    "reset",
    "main",
]

TRACEPARENT_VERSION = "00"

#: child-span names the serving path emits (the docs' span taxonomy)
SPAN_KINDS = ("queue_wait", "admit", "prefill", "prefill_chunk", "decode",
              "speculate", "evict", "cow", "stream")


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _gen_trace_id() -> str:
    return os.urandom(16).hex()


def _gen_span_id() -> str:
    return os.urandom(8).hex()


def _is_hex(s: str) -> bool:
    try:
        int(s, 16)
    except ValueError:
        return False
    return s == s.lower()


class TraceContext:
    """Serializable trace position: (trace id, parent span id, flags)."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: str, span_id: str, flags: int = 1):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = int(flags)

    def to_traceparent(self) -> str:
        return (f"{TRACEPARENT_VERSION}-{self.trace_id}-{self.span_id}"
                f"-{self.flags & 0xFF:02x}")

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"TraceContext({self.to_traceparent()!r})"


def parse_traceparent(value) -> TraceContext | None:
    """Parse a W3C ``traceparent`` header. Returns ``None`` (never
    raises) on anything malformed — a bad inbound header must degrade to
    a fresh trace, not fail the request."""
    if not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) or set(trace_id) == {"0"}:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or set(span_id) == {"0"}:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    return TraceContext(trace_id, span_id, int(flags, 16))


class Span:
    """One timed, attributed interval inside a request trace."""

    __slots__ = ("name", "span_id", "parent_id", "t_start", "t_end",
                 "attributes", "_trace")

    def __init__(self, name, parent_id=None, t_start=None, attributes=None,
                 _trace=None):
        self.name = name
        self.span_id = _gen_span_id()
        self.parent_id = parent_id
        self.t_start = time.time() if t_start is None else float(t_start)
        self.t_end = None
        self.attributes = dict(attributes) if attributes else {}
        self._trace = _trace

    def set(self, **attrs) -> "Span":
        self.attributes.update(attrs)
        return self

    def end(self, t_end=None, **attrs) -> None:
        if attrs:
            self.attributes.update(attrs)
        tr = self._trace
        if tr is not None:
            tr._end_span(self, t_end)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and "error" not in self.attributes:
            self.attributes["error"] = repr(exc)
        self.end()

    def to_dict(self) -> dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id, "t_start": self.t_start,
             "t_end": self.t_end}
        if self.attributes:
            d["attributes"] = dict(self.attributes)
        return d


class _NoopSpan:
    """Disabled-mode span: every method is a no-op returning a singleton
    (type identity: ``trace.span(...) is NOOP_SPAN`` always holds)."""

    __slots__ = ()
    name = None
    span_id = None
    parent_id = None
    t_start = None
    t_end = None
    attributes: dict = {}

    def set(self, **attrs):
        return self

    def end(self, t_end=None, **attrs):
        return None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return None

    def to_dict(self):
        return {}


class _NoopTrace:
    """Disabled-mode request trace (singleton, see :data:`NOOP_TRACE`)."""

    __slots__ = ()
    trace_id = None
    request_id = None

    def context(self):
        return None

    def span(self, name, parent=None, t_start=None, **attrs):
        return NOOP_SPAN

    def add_span(self, name, t_start, t_end, parent=None, **attrs):
        return NOOP_SPAN

    def finish(self, state="completed", **fields):
        return None

    def snapshot(self):
        return {}

    def open_spans(self):
        return []


NOOP_SPAN = _NoopSpan()
NOOP_TRACE = _NoopTrace()


class RequestTrace:
    """Span buffer for one request: a root span plus a bounded list of
    children. Thread-safe; the lock is a leaf (methods never call out
    of this module while holding it)."""

    def __init__(self, tracer, request_id=None, name="request",
                 parent: TraceContext | None = None, max_spans=256,
                 attributes=None):
        self._tracer = tracer
        self._lock = _tsan.lock("observability.tracing.RequestTrace")
        self.trace_id = parent.trace_id if parent else _gen_trace_id()
        self.request_id = request_id
        self.max_spans = int(max_spans)
        self.root = Span(name, parent_id=parent.span_id if parent else None,
                         attributes=attributes, _trace=self)
        if request_id is not None:
            self.root.attributes.setdefault("request_id", request_id)
        self._spans: list[Span] = []      # finished children, bounded
        self._open: dict[str, Span] = {}  # span_id -> open child
        self._dropped = 0
        self._cost_s = 0.0
        self._finished = False

    # -- span lifecycle -------------------------------------------------
    def context(self) -> TraceContext:
        """Context to propagate downstream (child of the root span)."""
        return TraceContext(self.trace_id, self.root.span_id)

    def span(self, name, parent=None, t_start=None, **attrs) -> Span:
        """Open a child span (ended via ``.end()`` / context manager)."""
        t0 = time.perf_counter()
        parent_id = parent.span_id if parent is not None else self.root.span_id
        s = Span(name, parent_id=parent_id, t_start=t_start,
                 attributes=attrs or None, _trace=self)
        with self._lock:
            if self._finished or \
                    len(self._spans) + len(self._open) >= self.max_spans:
                self._dropped += 1
                s._trace = None  # still usable, just not recorded
            else:
                self._open[s.span_id] = s
            self._cost_s += time.perf_counter() - t0
        return s

    def add_span(self, name, t_start, t_end, parent=None, **attrs) -> Span:
        """Record an already-timed span in one call (burst flushes)."""
        t0 = time.perf_counter()
        parent_id = parent.span_id if parent is not None else self.root.span_id
        s = Span(name, parent_id=parent_id, t_start=t_start,
                 attributes=attrs or None, _trace=None)
        s.t_end = float(t_end)
        with self._lock:
            if self._finished or len(self._spans) >= self.max_spans:
                self._dropped += 1
            else:
                self._spans.append(s)
            self._cost_s += time.perf_counter() - t0
        return s

    def _end_span(self, span: Span, t_end=None) -> None:
        t0 = time.perf_counter()
        end = time.time() if t_end is None else float(t_end)
        with self._lock:
            if span.t_end is None:
                span.t_end = end
            live = self._open.pop(span.span_id, None)
            if live is not None and not self._finished and \
                    len(self._spans) < self.max_spans:
                self._spans.append(span)
            elif live is not None:
                self._dropped += 1
            self._cost_s += time.perf_counter() - t0

    def finish(self, state="completed", **fields) -> dict | None:
        """Close the root span, build the request record and hand the
        trace to the tracer's reservoir + request log. Idempotent."""
        t0 = time.perf_counter()
        now = time.time()
        with self._lock:
            if self._finished:
                return None
            self._finished = True
            self.root.t_end = now
            # a still-open child at finish is a bug upstream, but the
            # trace must stay renderable: close it at root end
            for s in self._open.values():
                s.t_end = now
                s.attributes.setdefault("unfinished", True)
                if len(self._spans) < self.max_spans:
                    self._spans.append(s)
                else:
                    self._dropped += 1
            self._open.clear()
            spans = list(self._spans)
            dropped = self._dropped
            self._cost_s += time.perf_counter() - t0
            cost_s = self._cost_s
        record = self._build_record(state, spans, dropped, fields)
        # tracer lock taken strictly after the trace lock was released:
        # the two lock classes are never nested in either order
        self._tracer._complete(self, record, len(spans), cost_s)
        return record

    # -- introspection --------------------------------------------------
    def _build_record(self, state, spans, dropped, fields) -> dict:
        root = self.root
        e2e_s = (root.t_end or time.time()) - root.t_start
        record = {
            "trace_id": self.trace_id,
            "request_id": self.request_id,
            "state": state,
            "t_start": root.t_start,
            "t_end": root.t_end,
            "e2e_ms": round(e2e_s * 1000.0, 3),
            "spans": len(spans),
            "dropped_spans": dropped,
            "span_kinds": sorted({s.name for s in spans}),
            "span_coverage": round(_coverage(root, spans), 4),
        }
        proposed = sum(s.attributes.get("proposed", 0) for s in spans
                       if s.name == "speculate")
        if proposed:
            record["spec"] = {
                "proposed": proposed,
                "accepted": sum(s.attributes.get("accepted", 0)
                                for s in spans if s.name == "speculate")}
        for k, v in fields.items():
            if v is not None:
                record[k] = v
        return record

    def snapshot(self) -> dict:
        """Full span tree (finished + still-open children)."""
        with self._lock:
            spans = [s.to_dict() for s in self._spans]
            open_ = [s.to_dict() for s in self._open.values()]
            dropped = self._dropped
        d = {"trace_id": self.trace_id, "request_id": self.request_id,
             "root": self.root.to_dict(), "spans": spans}
        if open_:
            d["open"] = open_
        if dropped:
            d["dropped_spans"] = dropped
        return d

    def open_spans(self) -> list[dict]:
        """Spans without an end time (root included while unfinished),
        each stamped with trace/request ids — this is what a flight dump
        carries for an in-flight request at death."""
        out = []
        with self._lock:
            if self._finished:
                return out
            for s in [self.root] + list(self._open.values()):
                d = s.to_dict()
                d["trace_id"] = self.trace_id
                d["request_id"] = self.request_id
                out.append(d)
        return out


def _coverage(root, spans) -> float:
    """Fraction of the root span's wall covered by the union of child
    span intervals (the bench's span-coverage acceptance stat)."""
    t0, t1 = root.t_start, root.t_end or time.time()
    if t1 <= t0:
        return 1.0 if spans else 0.0
    ivals = []
    for s in spans:
        a = max(s.t_start, t0)
        b = min(s.t_end if s.t_end is not None else t1, t1)
        if b > a:
            ivals.append((a, b))
    ivals.sort()
    covered = 0.0
    cur_a = cur_b = None
    for a, b in ivals:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        covered += cur_b - cur_a
    return min(1.0, covered / (t1 - t0))


class Tracer:
    """Process-global trace collector: live traces, a sampled reservoir
    of completed traces, a ring of request-log records and histogram
    exemplars. All state behind one leaf lock."""

    def __init__(self, enabled=None, max_spans=None, reservoir=None,
                 log_capacity=None, sample_every=None):
        if enabled is None:
            enabled = os.environ.get("PADDLE_TPU_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        self.max_spans = max_spans if max_spans is not None else \
            _env_int("PADDLE_TPU_TRACE_SPANS", 256)
        self.reservoir_capacity = reservoir if reservoir is not None else \
            _env_int("PADDLE_TPU_TRACE_RESERVOIR", 256)
        self.log_capacity = log_capacity if log_capacity is not None else \
            _env_int("PADDLE_TPU_TRACE_REQUESTS", 512)
        #: keep every Nth completed trace's full span tree (the request
        #: log line is always written); deterministic counter sampling
        self.sample_every = max(1, sample_every if sample_every is not None
                                else _env_int("PADDLE_TPU_TRACE_SAMPLE", 1))
        self._lock = _tsan.lock("observability.tracing.Tracer")
        self._live: "OrderedDict[str, RequestTrace]" = OrderedDict()
        self._live_capacity = max(64, self.reservoir_capacity * 4)
        self._reservoir: "OrderedDict[str, dict]" = OrderedDict()
        self._log: deque = deque(maxlen=self.log_capacity)
        self._exemplars: dict[str, dict] = {}
        self._completions = 0
        self._spans_total = 0
        self._dropped_live = 0
        self._cost_s = 0.0

    # -- request lifecycle ----------------------------------------------
    def start_request(self, request_id=None, traceparent=None, **attrs):
        """Open a root span. Returns :data:`NOOP_TRACE` when disabled
        (identity-checkable by hot call sites). A malformed
        ``traceparent`` yields a fresh trace, never an error."""
        if not self.enabled:
            return NOOP_TRACE
        parent = parse_traceparent(traceparent) if traceparent else None
        tr = RequestTrace(self, request_id=request_id, parent=parent,
                          max_spans=self.max_spans, attributes=attrs or None)
        with self._lock:
            self._live[tr.trace_id] = tr
            while len(self._live) > self._live_capacity:
                self._live.popitem(last=False)
                self._dropped_live += 1
        return tr

    def _complete(self, tr, record, n_spans, cost_s) -> None:
        with self._lock:
            self._live.pop(tr.trace_id, None)
            self._completions += 1
            self._spans_total += n_spans
            self._cost_s += cost_s
            self._log.append(record)
            if (self._completions - 1) % self.sample_every == 0:
                self._reservoir[tr.trace_id] = None  # snapshot outside lock
                while len(self._reservoir) > self.reservoir_capacity:
                    self._reservoir.popitem(last=False)
            keep = tr.trace_id in self._reservoir
        if keep:
            snap = tr.snapshot()
            snap["record"] = record
            with self._lock:
                if tr.trace_id in self._reservoir:
                    self._reservoir[tr.trace_id] = snap

    # -- lookups ---------------------------------------------------------
    def get_trace(self, trace_id) -> dict | None:
        """Span tree for a trace id: completed (reservoir) or live."""
        with self._lock:
            snap = self._reservoir.get(trace_id)
            live = self._live.get(trace_id)
        if snap is not None:
            return snap
        if live is not None:
            return live.snapshot()
        return None

    def requests(self, last=None) -> list[dict]:
        """Most recent request-log records, oldest first."""
        with self._lock:
            out = list(self._log)
        if last is not None and last >= 0:
            out = out[-last:]
        return out

    def open_spans(self) -> list[dict]:
        """Open spans of every in-flight trace (flight-dump payload)."""
        with self._lock:
            live = list(self._live.values())
        out = []
        for tr in live:
            out.extend(tr.open_spans())
        return out

    # -- exemplars --------------------------------------------------------
    def note_exemplar(self, metric, value, trace_id, buckets=()) -> None:
        """Link ``value`` observed on ``metric`` to a trace id, keyed by
        the histogram bucket it falls in (latest observation per bucket
        wins; bounded by the bucket count)."""
        if trace_id is None:
            return
        le = "+Inf"
        for b in buckets:
            if value <= b:
                le = b
                break
        with self._lock:
            self._exemplars.setdefault(metric, {})[str(le)] = {
                "bucket_le": le, "value": round(float(value), 3),
                "trace_id": trace_id, "t": time.time()}

    def exemplars(self) -> dict:
        """Per metric: exemplar per occupied bucket plus a ``top``
        pointer at the highest occupied bucket (the p99 explainer)."""
        with self._lock:
            snap = {m: dict(bs) for m, bs in self._exemplars.items()}
        out = {}
        for metric, bs in snap.items():
            def _key(item):
                le = item[1]["bucket_le"]
                return float("inf") if le == "+Inf" else float(le)
            top = max(bs.items(), key=_key)[1]
            out[metric] = {"buckets": bs, "top": top}
        return out

    # -- maintenance ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            n_spans = self._spans_total
            cost = self._cost_s
            return {
                "enabled": self.enabled,
                "live": len(self._live),
                "reservoir": len(self._reservoir),
                "completions": self._completions,
                "spans_total": n_spans,
                "dropped_live": self._dropped_live,
                "cost_s": round(cost, 6),
                "span_cost_us": round(cost / n_spans * 1e6, 3)
                if n_spans else 0.0,
            }

    def flight_snapshot(self) -> dict:
        """Bounded payload the flight recorder embeds in every dump:
        open spans of in-flight requests + a tail of recent traces."""
        with self._lock:
            recent = [s for s in list(self._reservoir.values())[-8:]
                      if s is not None]
            log_tail = list(self._log)[-16:]
        return {"open_spans": self.open_spans(), "traces": recent,
                "requests": log_tail, "stats": self.stats()}

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._reservoir.clear()
            self._log.clear()
            self._exemplars.clear()
            self._completions = 0
            self._spans_total = 0
            self._dropped_live = 0
            self._cost_s = 0.0


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def tracing_enabled() -> bool:
    return _TRACER.enabled


def enable(on: bool = True) -> None:
    """Flip tracing at runtime (``PADDLE_TPU_TRACE`` sets the default).
    Already-open traces keep recording; new requests observe the flag."""
    _TRACER.enabled = bool(on)


def start_request(request_id=None, traceparent=None, **attrs):
    return _TRACER.start_request(request_id=request_id,
                                 traceparent=traceparent, **attrs)


def get_trace(trace_id):
    return _TRACER.get_trace(trace_id)


def requests(last=None):
    return _TRACER.requests(last)


def open_spans():
    return _TRACER.open_spans()


def note_exemplar(metric, value, trace_id, buckets=()):
    _TRACER.note_exemplar(metric, value, trace_id, buckets)


def exemplars():
    return _TRACER.exemplars()


def flight_snapshot():
    return _TRACER.flight_snapshot()


def stats():
    return _TRACER.stats()


def reset():
    _TRACER.reset()


#: burst length for decode/speculate span aggregation (spans per burst)
def decode_burst() -> int:
    return max(1, _env_int("PADDLE_TPU_TRACE_BURST", 32))


# ---------------------------------------------------------------------------
# Exporters


def render_request_log(last=None) -> str:
    """The structured request log: one strict-JSON (RFC 8259) line per
    completed request, sanitised with the flight recorder's encoders."""
    from .. import flight as _flight
    lines = []
    for rec in _TRACER.requests(last):
        lines.append(json.dumps(_flight._finite(rec), sort_keys=True,
                                allow_nan=False,
                                default=_flight._json_safe))
    return "\n".join(lines) + ("\n" if lines else "")


def to_chrome_trace(traces, open_spans=(), trace=None) -> dict:
    """Render trace snapshots (+ loose open spans) as Chrome-trace JSON,
    merged into ``trace`` if given. Conventions match the flight
    exporter: closed spans are ``ph:"X"`` complete events; spans without
    an end (a dying process's in-flight requests) are kept as ``ph:"B"``
    begin events rather than dropped."""
    out = trace if trace is not None else {"traceEvents": [],
                                           "displayTimeUnit": "ms"}
    events = out.setdefault("traceEvents", [])
    tids: dict[str, int] = {}

    def _tid(trace_id):
        return tids.setdefault(trace_id, len(tids) + 1)

    def _emit(span, trace_id, request_id):
        args = dict(span.get("attributes") or {})
        args["trace_id"] = trace_id
        args["span_id"] = span.get("span_id")
        if request_id is not None:
            args.setdefault("request_id", request_id)
        ev = {"name": span.get("name"), "cat": "request", "pid": 1,
              "tid": _tid(trace_id),
              "ts": round(float(span["t_start"]) * 1e6, 1), "args": args}
        if span.get("t_end") is not None:
            ev["ph"] = "X"
            ev["dur"] = round((float(span["t_end"]) -
                               float(span["t_start"])) * 1e6, 1)
        else:
            ev["ph"] = "B"  # open at death: keep, flight-style
        events.append(ev)

    for snap in traces or ():
        trace_id = snap.get("trace_id")
        request_id = snap.get("request_id")
        root = snap.get("root")
        if root:
            _emit(root, trace_id, request_id)
        for s in snap.get("spans") or ():
            _emit(s, trace_id, request_id)
        for s in snap.get("open") or ():
            _emit(s, trace_id, request_id)
    for s in open_spans or ():
        _emit(s, s.get("trace_id"), s.get("request_id"))
    return out


def _tracing_sections(payload: dict) -> tuple[list, list]:
    """Pull (traces, open_spans) out of a flight dump payload — both the
    dump-time snapshot and the at-preemption snapshot the engine stashes
    in ``extra`` — or out of a raw ``flight_snapshot()`` file."""
    traces, spans = [], []
    for section in (payload.get("tracing"),
                    (payload.get("extra") or {}).get("tracing_at_preempt"),
                    payload if "open_spans" in payload or "traces" in payload
                    else None):
        if not isinstance(section, dict):
            continue
        traces.extend(section.get("traces") or ())
        # open spans stay even when the same trace also completed later
        # (a drain finishing the request does not erase what was in
        # flight at the signal) — the keep-unmatched-spans convention
        spans.extend(section.get("open_spans") or ())
    return traces, spans


def main(argv=None) -> int:
    """CLI: summarize / re-render the tracing payload of a flight dump.

    ``python -m paddle_tpu.observability.tracing dump.json`` prints the
    request records and open spans; ``--chrome-trace out.json`` writes a
    chrome://tracing file (open spans kept as ``B`` events); ``--json``
    dumps the raw sections.
    """
    import argparse
    ap = argparse.ArgumentParser(prog="paddle_tpu.observability.tracing",
                                 description=main.__doc__)
    ap.add_argument("path", help="flight dump json (or a raw "
                                 "flight_snapshot() file)")
    ap.add_argument("--chrome-trace", metavar="OUT",
                    help="write Chrome-trace JSON to OUT")
    ap.add_argument("--json", action="store_true",
                    help="print the raw tracing sections as JSON")
    ap.add_argument("--last", type=int, default=None,
                    help="only the most recent N request records")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        print(f"tracing: cannot read {args.path!r}: {e}")
        return 2
    traces, spans = _tracing_sections(payload)
    records = []
    for section in (payload.get("tracing"),
                    (payload.get("extra") or {}).get("tracing_at_preempt"),
                    payload if "requests" in payload else None):
        if isinstance(section, dict):
            records.extend(section.get("requests") or ())
    if args.last is not None:
        records = records[-args.last:]
    if args.json:
        print(json.dumps({"traces": traces, "open_spans": spans,
                          "requests": records}, indent=2, sort_keys=True))
    else:
        print(f"tracing: {len(records)} request record(s), "
              f"{len(traces)} trace snapshot(s), "
              f"{len(spans)} open span(s)")
        for r in records:
            print(f"  [{r.get('state', '?'):>9}] trace={r.get('trace_id')} "
                  f"req={r.get('request_id')} e2e={r.get('e2e_ms')}ms "
                  f"queue={r.get('queue_ms')}ms "
                  f"prefill={r.get('prefill_ms')}ms "
                  f"decode={r.get('decode_ms')}ms "
                  f"coverage={r.get('span_coverage')}")
        for s in spans:
            print(f"  [open] {s.get('name')} trace={s.get('trace_id')} "
                  f"req={s.get('request_id')} since={s.get('t_start')}")
    if args.chrome_trace:
        ct = to_chrome_trace(traces, spans)
        with open(args.chrome_trace, "w") as f:
            json.dump(ct, f)
        print(f"tracing: wrote {len(ct['traceEvents'])} event(s) to "
              f"{args.chrome_trace}")
    return 0
