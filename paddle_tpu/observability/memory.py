"""HBM memory profiler: device-stats census, live-array census, and
per-module peak attribution.

Answers the question the fused-XLA/GSPMD execution model makes
unanswerable from logs: *which arrays — and which ``nn.Layer`` — own the
HBM that ran out*. Three tools:

* :func:`census` — one shot: ``device.memory_stats()`` for every device
  plus a ``jax.live_arrays()`` walk aggregated by (dtype, shape), exported
  as ``paddle_tpu_hbm_bytes{kind=...}`` gauges and returned JSON-safe (the
  flight recorder embeds it in every dump).
* :class:`MemorySampler` — periodic census on a step cadence for training
  loops (one ``maybe_sample(step)`` call per step, a real census every
  ``every`` steps).
* :func:`attribute_memory` — a context manager that hooks every sublayer's
  forward (``register_forward_pre_hook``/``register_forward_post_hook``)
  and attributes per-module allocation deltas and peaks. Run it around ONE
  eager forward — under ``to_static`` the whole step is a single fused
  program and module boundaries don't exist on device. The latest
  attribution table is kept module-global so flight dumps carry it.

Import-time stdlib-only like the rest of the package; jax is imported
lazily inside the functions that walk device state.
"""

from __future__ import annotations

import threading

from . import metrics as _m

__all__ = ["census", "device_memory_stats", "live_array_census",
           "MemorySampler", "attribute_memory", "last_attribution",
           "current_bytes", "format_bytes"]


def format_bytes(n) -> str:
    """Human-readable byte count (shared by the flight CLI and the
    profiler's summary tables, so both render quantities identically)."""
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{int(n)} B" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TB"

_G_HBM = _m.gauge(
    "paddle_tpu_hbm_bytes",
    "device memory bytes by kind (in_use|peak|limit|live_arrays)")
_G_LIVE = _m.gauge(
    "paddle_tpu_hbm_live_arrays",
    "count of live device arrays at the last census")
_C_CENSUS = _m.counter(
    "paddle_tpu_hbm_census_total", "memory censuses taken")


def device_memory_stats(device=None) -> dict:
    """``memory_stats()`` of one device (default: device 0), ``{}`` when
    the backend exposes none (XLA:CPU)."""
    try:
        import jax
        d = jax.devices()[0] if device is None else device
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def live_array_census(top: int = 20) -> dict:
    """Aggregate ``jax.live_arrays()`` by (dtype, shape): the owner-level
    view of what is actually resident. Returns ``{"count", "total_bytes",
    "by_dtype_shape": [{"dtype", "shape", "count", "bytes"}, ...]}`` with
    rows sorted by bytes descending, trimmed to ``top``."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:
        return {"count": 0, "total_bytes": 0, "by_dtype_shape": []}
    agg: dict = {}
    total = 0
    for a in arrs:
        try:
            nbytes = int(a.nbytes)
            key = (str(a.dtype), tuple(a.shape))
        except Exception:
            continue
        total += nbytes
        row = agg.get(key)
        if row is None:
            agg[key] = [1, nbytes]
        else:
            row[0] += 1
            row[1] += nbytes
    rows = [{"dtype": k[0], "shape": list(k[1]), "count": v[0],
             "bytes": v[1]} for k, v in agg.items()]
    rows.sort(key=lambda r: (-r["bytes"], r["dtype"], r["shape"]))
    return {"count": len(arrs), "total_bytes": total,
            "by_dtype_shape": rows[:top]}


def current_bytes() -> int:
    """Best available 'bytes resident now': allocator ``bytes_in_use``
    where the backend reports it, else the live-array total (XLA:CPU) —
    the probe :func:`attribute_memory` diffs around each forward."""
    stats = device_memory_stats()
    b = int(stats.get("bytes_in_use", 0))
    if b:
        return b
    return live_array_census(top=0)["total_bytes"]


def census(top: int = 20) -> dict:
    """Full memory census: device stats + live-array aggregation, exported
    to the ``paddle_tpu_hbm_bytes{kind=...}`` gauges and returned."""
    stats = device_memory_stats()
    live = live_array_census(top=top)
    _C_CENSUS.inc()
    if stats.get("bytes_in_use") is not None:
        _G_HBM.set(int(stats["bytes_in_use"]), kind="in_use")
    if stats.get("peak_bytes_in_use") is not None:
        _G_HBM.set(int(stats["peak_bytes_in_use"]), kind="peak")
    if stats.get("bytes_limit") is not None:
        _G_HBM.set(int(stats["bytes_limit"]), kind="limit")
    _G_HBM.set(live["total_bytes"], kind="live_arrays")
    _G_LIVE.set(live["count"])
    return {"device": {k: int(v) for k, v in stats.items()
                       if isinstance(v, (int, float))},
            "live_arrays": live}


class MemorySampler:
    """Step-cadence census for training loops::

        sampler = MemorySampler(every=50)
        for step in ...:
            ...
            sampler.maybe_sample(step)

    Off-cadence calls cost one modulo; on cadence one :func:`census`
    (a live-array walk — keep ``every`` large on huge graphs)."""

    def __init__(self, every: int = 50, top: int = 20):
        if every < 1:
            raise ValueError("every must be >= 1")
        self.every = every
        self.top = top
        self.last: dict | None = None

    def maybe_sample(self, step: int) -> dict | None:
        if step % self.every:
            return None
        self.last = census(top=self.top)
        return self.last

    def sample(self) -> dict:
        self.last = census(top=self.top)
        return self.last


# ---------------------------------------------------------------------------
# per-module attribution
# ---------------------------------------------------------------------------

_attr_lock = threading.Lock()
_last_attribution: dict = {}


def last_attribution() -> dict:
    """The most recent :func:`attribute_memory` table (flight dumps embed
    this): ``{module_path: {"calls", "last_delta_bytes",
    "peak_delta_bytes", "peak_bytes"}}``."""
    with _attr_lock:
        return {k: dict(v) for k, v in _last_attribution.items()}


class attribute_memory:
    """Attribute allocation deltas to the ``nn.Layer`` that made them::

        with attribute_memory(model) as attr:
            model(x)                      # ONE eager forward
        attr.peaks                        # {path: {...bytes stats...}}
        print(attr.table())

    Each sublayer gets a forward pre-hook (record bytes-resident on entry)
    and post-hook (delta on exit). ``peak_delta_bytes`` is the largest
    single-call delta per module; ``peak_bytes`` the highest absolute
    level seen at any of its boundaries. Nested modules both observe an
    allocation made by the inner one — read the table leaf-first.

    Hooks are removed on exit and the table is published to
    :func:`last_attribution` so a later crash dump still carries it.
    """

    def __init__(self, model, probe=None):
        self.model = model
        self.peaks: dict = {}
        self._probe = probe or current_bytes
        self._handles: list = []
        self._entry: dict = {}

    def _path_of(self, prefix, layer):
        return prefix or layer.__class__.__name__

    def __enter__(self):
        named = [("", self.model)]
        try:
            named += list(self.model.named_sublayers())
        except Exception:
            pass
        for prefix, layer in named:
            path = self._path_of(prefix, layer)

            def pre(layer_, inputs, _path=path):
                self._entry.setdefault(_path, []).append(self._probe())

            def post(layer_, inputs, out, _path=path):
                stack = self._entry.get(_path)
                before = stack.pop() if stack else 0
                now = self._probe()
                st = self.peaks.setdefault(_path, {
                    "calls": 0, "last_delta_bytes": 0,
                    "peak_delta_bytes": 0, "peak_bytes": 0})
                delta = now - before
                st["calls"] += 1
                st["last_delta_bytes"] = delta
                st["peak_delta_bytes"] = max(st["peak_delta_bytes"], delta)
                st["peak_bytes"] = max(st["peak_bytes"], now, before)

            self._handles.append(layer.register_forward_pre_hook(pre))
            self._handles.append(layer.register_forward_post_hook(post))
        return self

    def __exit__(self, *exc):
        for h in self._handles:
            try:
                h.remove()
            except Exception:
                pass
        self._handles.clear()
        global _last_attribution
        with _attr_lock:
            _last_attribution = {k: dict(v) for k, v in self.peaks.items()}
        return False

    def table(self, top: int = 20) -> str:
        rows = sorted(self.peaks.items(),
                      key=lambda kv: -kv[1]["peak_delta_bytes"])[:top]
        out = [f"{'module':<40} {'calls':>5} {'peak delta':>14} "
               f"{'peak bytes':>14}"]
        for name, st in rows:
            out.append(f"{name:<40} {st['calls']:>5} "
                       f"{st['peak_delta_bytes']:>14} {st['peak_bytes']:>14}")
        return "\n".join(out)
