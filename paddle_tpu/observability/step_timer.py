"""StepTimer: per-step latency / throughput / MFU telemetry.

Holds the analytic-FLOPs MFU math and the per-generation peak-FLOPs table
that bench.py established (BASELINE.md discipline: MFU = tokens/s x
FLOPs/token / spec-sheet peak), so the bench and any training loop report
the same number from the same formula.

Stdlib-only: the device argument is duck-typed on ``.platform`` /
``.device_kind`` — no jax import here.
"""

from __future__ import annotations

import os
import time

from . import flight as _flight
from . import metrics as _m

__all__ = ["StepTimer", "device_peak_flops", "analytic_mfu",
           "PEAK_FLOPS_TABLE"]

# bf16 peak FLOPs per chip by generation (spec sheets).
PEAK_FLOPS_TABLE = {
    "v6e": 918e12, "v6": 918e12, "v5p": 459e12, "v5e": 197e12,
    "v5litepod": 197e12, "v5 lite": 197e12, "v5lite": 197e12,
    "v4": 275e12, "v3": 123e12, "v2": 45e12,
}


def device_peak_flops(device=None, device_kind=None, platform=None,
                      env=None):
    """(bf16 peak FLOPs, source string) for a device (duck-typed) or an
    explicit (device_kind, platform) pair. Non-TPU platforms report 0.0 so
    MFU degrades to 0 rather than garbage."""
    env = os.environ if env is None else env
    if device is not None:
        device_kind = getattr(device, "device_kind", "") or ""
        platform = getattr(device, "platform", "")
    kind = (device_kind or "").lower()
    if platform not in ("tpu", "axon"):
        return 0.0, "cpu"
    for k, v in PEAK_FLOPS_TABLE.items():
        if k in kind:
            return v, f"device_kind:{kind}"
    gen = env.get("PALLAS_AXON_TPU_GEN", "").lower()
    for k, v in PEAK_FLOPS_TABLE.items():
        if k in gen:
            return v, f"env:PALLAS_AXON_TPU_GEN={gen}"
    return PEAK_FLOPS_TABLE["v5e"], "default_guess_v5e"


def analytic_mfu(tokens_per_sec, flops_per_token, peak_flops):
    """Model-FLOPs utilization from analytic per-token FLOPs (bench.py's
    6N + attention-correction counts) against the spec-sheet peak."""
    if not peak_flops or not flops_per_token:
        return 0.0
    return tokens_per_sec * flops_per_token / peak_flops


class StepTimer:
    """Record train/serve step telemetry into the registry.

    Two usage modes:

    * per-step context manager — each ``with`` block is one step::

          timer = StepTimer("train", tokens_per_step=b * s,
                            flops_per_token=model.flops_per_token(s) * 3,
                            peak_flops=peak)
          for batch in loader:
              with timer:
                  train_step(batch)

    * externally-timed window (the bench pattern: N steps timed around a
      single device sync, no per-step blocking)::

          timer.record_window(steps=N, tokens=b * s * N, seconds=dt)

    Metrics: ``paddle_tpu_step_seconds`` histogram (per-step latency),
    ``paddle_tpu_step_total`` counter, ``paddle_tpu_step_tokens_per_second``
    + ``paddle_tpu_step_mfu_ratio`` gauges, and
    ``paddle_tpu_step_transfer_bytes_total`` for host<->device traffic fed
    in via :meth:`record_transfer`. All carry a ``name`` label.
    """

    def __init__(self, name: str = "train", tokens_per_step=None,
                 flops_per_token=None, peak_flops=None, registry=None):
        self.name = name
        self.tokens_per_step = tokens_per_step
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops
        reg = registry or _m.get_registry()
        self._h_step = reg.histogram(
            "paddle_tpu_step_seconds", "per-step wall latency")
        self._c_steps = reg.counter(
            "paddle_tpu_step_total", "steps recorded")
        self._g_tps = reg.gauge(
            "paddle_tpu_step_tokens_per_second",
            "throughput of the most recent recorded step/window")
        self._g_mfu = reg.gauge(
            "paddle_tpu_step_mfu_ratio",
            "analytic-FLOPs model-FLOPs utilization of the most recent "
            "recorded step/window")
        self._c_transfer = reg.counter(
            "paddle_tpu_step_transfer_bytes_total",
            "host<->device transfer bytes attributed to steps")
        self.last_step_s = None
        self.tokens_per_sec = None
        self.mfu = None
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.record_window(1, self.tokens_per_step,
                           time.perf_counter() - self._t0)
        self._t0 = None
        return False

    def record_window(self, steps: int, tokens, seconds: float) -> dict:
        """Fold an externally-timed window of ``steps`` steps covering
        ``tokens`` tokens (None if tokens don't apply) into the metrics;
        returns the derived stats."""
        steps = max(int(steps), 1)
        step_s = seconds / steps
        self.last_step_s = step_s
        self._h_step.observe(step_s, name=self.name)
        self._c_steps.inc(steps, name=self.name)
        stats = {"step_seconds": step_s, "steps": steps}
        if _flight.enabled():  # one event per step/window: the black box's
            # step-timing heartbeat
            _flight.record("step", name=self.name, steps=steps,
                           step_seconds=round(step_s, 6))
        if tokens and seconds > 0:
            self.tokens_per_sec = tokens / seconds
            self._g_tps.set(self.tokens_per_sec, name=self.name)
            stats["tokens_per_sec"] = self.tokens_per_sec
            self.mfu = analytic_mfu(self.tokens_per_sec,
                                    self.flops_per_token, self.peak_flops)
            if self.mfu:
                self._g_mfu.set(self.mfu, name=self.name)
                stats["mfu"] = self.mfu
        return stats

    def record_transfer(self, nbytes: int):
        """Attribute host<->device transfer bytes to this timer's step."""
        self._c_transfer.inc(int(nbytes), name=self.name)
