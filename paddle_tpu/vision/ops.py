"""Vision ops (reference: python/paddle/vision/ops.py — nms, box utils)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.function import apply
from ..core.tensor import Tensor, as_tensor

__all__ = ["nms", "box_area", "box_iou"]


def box_area(boxes):
    def f(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply(f, boxes, name="box_area")


def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = (x2 - x1) * (y2 - y1)
    xx1 = jnp.maximum(x1[:, None], x1[None, :])
    yy1 = jnp.maximum(y1[:, None], y1[None, :])
    xx2 = jnp.minimum(x2[:, None], x2[None, :])
    yy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-9)


def box_iou(boxes1, boxes2):
    def f(b1, b2):
        x11, y11, x12, y12 = (b1[:, i] for i in range(4))
        x21, y21, x22, y22 = (b2[:, i] for i in range(4))
        a1 = (x12 - x11) * (y12 - y11)
        a2 = (x22 - x21) * (y22 - y21)
        xx1 = jnp.maximum(x11[:, None], x21[None, :])
        yy1 = jnp.maximum(y11[:, None], y21[None, :])
        xx2 = jnp.minimum(x12[:, None], x22[None, :])
        yy2 = jnp.minimum(y12[:, None], y22[None, :])
        inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
        return inter / jnp.maximum(a1[:, None] + a2[None, :] - inter, 1e-9)
    return apply(f, boxes1, boxes2, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS as a fixed-trip lax loop (static shapes: TPU-compilable).
    Returns kept indices sorted by score (reference vision/ops.py nms)."""
    b = as_tensor(boxes)._data.astype(jnp.float32)
    n = b.shape[0]
    s = as_tensor(scores)._data if scores is not None \
        else jnp.arange(n, 0, -1, dtype=jnp.float32)
    if category_idxs is not None:
        # per-category NMS (reference contract): translate each category's
        # boxes to a disjoint region so cross-category IoU is zero
        cat = as_tensor(category_idxs)._data.astype(jnp.float32)
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cat * span)[:, None]

    iou = _iou_matrix(b)
    order = jnp.argsort(-s)

    ranks = jnp.empty_like(order).at[order].set(jnp.arange(n))

    def body(i, keep):
        # box at score-rank i, if still kept, suppresses every lower-ranked
        # box overlapping it beyond the threshold
        oi = order[i]
        kill = (iou[oi] > iou_threshold) & (ranks > i) & keep[oi]
        return jnp.where(kill, False, keep)

    keep = jnp.ones((n,), bool)
    keep = jax.lax.fori_loop(0, n, body, keep)
    kept_sorted = order[keep[order]]
    idx = kept_sorted if top_k is None else kept_sorted[:top_k]
    return Tensor(idx, stop_gradient=True)
