"""Vision ops (reference: python/paddle/vision/ops.py — nms, box utils)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd.function import apply
from ..core.tensor import Tensor, as_tensor

from .detection_ops import (  # noqa: F401
    yolo_loss, yolo_box, prior_box, box_coder, matrix_nms,
    generate_proposals, distribute_fpn_proposals, psroi_pool, read_file,
    decode_jpeg, DeformConv2D, RoIAlign, RoIPool, PSRoIPool)

__all__ = ["nms", "box_area", "box_iou", "roi_align", "roi_pool",
           "deform_conv2d",
           # detection family (reference vision/ops.py:29 __all__)
           "yolo_loss", "yolo_box", "prior_box", "box_coder", "DeformConv2D",
           "distribute_fpn_proposals", "generate_proposals", "read_file",
           "decode_jpeg", "RoIPool", "psroi_pool", "PSRoIPool", "RoIAlign",
           "matrix_nms"]


def box_area(boxes):
    def f(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply(f, boxes, name="box_area")


def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = (x2 - x1) * (y2 - y1)
    xx1 = jnp.maximum(x1[:, None], x1[None, :])
    yy1 = jnp.maximum(y1[:, None], y1[None, :])
    xx2 = jnp.minimum(x2[:, None], x2[None, :])
    yy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-9)


def box_iou(boxes1, boxes2):
    def f(b1, b2):
        x11, y11, x12, y12 = (b1[:, i] for i in range(4))
        x21, y21, x22, y22 = (b2[:, i] for i in range(4))
        a1 = (x12 - x11) * (y12 - y11)
        a2 = (x22 - x21) * (y22 - y21)
        xx1 = jnp.maximum(x11[:, None], x21[None, :])
        yy1 = jnp.maximum(y11[:, None], y21[None, :])
        xx2 = jnp.minimum(x12[:, None], x22[None, :])
        yy2 = jnp.minimum(y12[:, None], y22[None, :])
        inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
        return inter / jnp.maximum(a1[:, None] + a2[None, :] - inter, 1e-9)
    return apply(f, boxes1, boxes2, name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS as a fixed-trip lax loop (static shapes: TPU-compilable).
    Returns kept indices sorted by score (reference vision/ops.py nms)."""
    b = as_tensor(boxes)._data.astype(jnp.float32)
    n = b.shape[0]
    s = as_tensor(scores)._data if scores is not None \
        else jnp.arange(n, 0, -1, dtype=jnp.float32)
    if category_idxs is not None:
        # per-category NMS (reference contract): translate each category's
        # boxes to a disjoint region so cross-category IoU is zero
        cat = as_tensor(category_idxs)._data.astype(jnp.float32)
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cat * span)[:, None]

    iou = _iou_matrix(b)
    order = jnp.argsort(-s)

    ranks = jnp.empty_like(order).at[order].set(jnp.arange(n))

    def body(i, keep):
        # box at score-rank i, if still kept, suppresses every lower-ranked
        # box overlapping it beyond the threshold
        oi = order[i]
        kill = (iou[oi] > iou_threshold) & (ranks > i) & keep[oi]
        return jnp.where(kill, False, keep)

    keep = jnp.ones((n,), bool)
    keep = jax.lax.fori_loop(0, n, body, keep)
    kept_sorted = order[keep[order]]
    idx = kept_sorted if top_k is None else kept_sorted[:top_k]
    return Tensor(idx, stop_gradient=True)


def _bilinear_axis(coord, size):
    """Shared bilinear-tap math (reference bilinear_interpolate semantics):
    samples beyond (-1, size) are invalid (zero contribution), inside ones
    clamp to the border pixel. Returns (valid, lo_idx, hi_idx, hi_weight)
    for one coordinate array of any shape; used by roi_align (separable
    grids) and deform_conv2d (pointwise grids)."""
    valid = (coord > -1.0) & (coord < size)
    cc = jnp.clip(coord, 0.0, size - 1.0)
    lo = jnp.floor(cc).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, size - 1)
    return valid, lo, hi, cc - lo


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None) -> Tensor:
    """RoI Align (reference: python/paddle/vision/ops.py roi_align over
    phi roi_align kernels). x: [N, C, H, W]; boxes: [R, 4] (x1,y1,x2,y2);
    boxes_num: [N] rois per image. Bilinear sampling on a fixed grid —
    gather + weighted sum, fully static shapes for the MXU-friendly path."""
    import numpy as np

    x_t, boxes_t = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = np.asarray(as_tensor(boxes_num).numpy(), np.int64)
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn))

    if sampling_ratio > 0:
        ns = int(sampling_ratio)
    else:
        # reference adaptive rule ceil(roi_size / pooled_size), which is
        # per-RoI; the grid must be static under vmap/jit, so use the max
        # over the (eager, host-visible) boxes, capped to bound compute
        bnp = np.asarray(boxes_t.numpy(), np.float64)
        rh_max = float(np.max((bnp[:, 3] - bnp[:, 1]) * spatial_scale,
                              initial=1.0))
        rw_max = float(np.max((bnp[:, 2] - bnp[:, 0]) * spatial_scale,
                              initial=1.0))
        ns = int(np.clip(np.ceil(max(rh_max / ph, rw_max / pw)), 1, 8))

    def f(xa, ba):
        n, c, hgt, wid = xa.shape
        r = ba.shape[0]
        half = 0.5 if aligned else 0.0
        x1 = ba[:, 0] * spatial_scale - half
        y1 = ba[:, 1] * spatial_scale - half
        x2 = ba[:, 2] * spatial_scale - half
        y2 = ba[:, 3] * spatial_scale - half
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sample grid: [R, ph*ns] y coords, [R, pw*ns] x coords
        iy = (jnp.arange(ph * ns) + 0.5) / ns
        ix = (jnp.arange(pw * ns) + 0.5) / ns
        ys = y1[:, None] + bin_h[:, None] * iy[None, :]   # [R, ph*ns]
        xs = x1[:, None] + bin_w[:, None] * ix[None, :]   # [R, pw*ns]

        def bilinear(img, yy, xx):
            # img: [C, H, W]; yy: [Sy], xx: [Sx] -> [C, Sy, Sx] (separable
            # grid: 1-D taps combined by outer product)
            vy, y0, y1i, wy = _bilinear_axis(yy, hgt)
            vx, x0, x1i, wx = _bilinear_axis(xx, wid)
            g = lambda yi, xi: img[:, yi, :][:, :, xi]
            top = g(y0, x0) * (1 - wx)[None, None, :] + \
                g(y0, x1i) * wx[None, None, :]
            bot = g(y1i, x0) * (1 - wx)[None, None, :] + \
                g(y1i, x1i) * wx[None, None, :]
            out = top * (1 - wy)[None, :, None] + bot * wy[None, :, None]
            return out * (vy[:, None] & vx[None, :])[None]

        def per_roi(ri):
            img = xa[img_of_roi[ri]]
            sampled = bilinear(img, ys[ri], xs[ri])       # [C, ph*ns, pw*ns]
            return sampled.reshape(c, ph, ns, pw, ns).mean((2, 4))

        return jax.vmap(per_roi)(jnp.arange(r))           # [R, C, ph, pw]

    return apply(f, x_t, boxes_t, name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoI max-pool (reference: vision/ops.py roi_pool): roi_align with max
    reduction semantics approximated by dense bilinear sampling + max."""
    import numpy as np

    x_t, boxes_t = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bn = np.asarray(as_tensor(boxes_num).numpy(), np.int64)
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn))

    def f(xa, ba):
        n, c, hgt, wid = xa.shape
        x1 = jnp.floor(ba[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.floor(ba[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.ceil(ba[:, 2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.ceil(ba[:, 3] * spatial_scale).astype(jnp.int32)

        ns = 4  # static sample grid per output bin
        iy = (jnp.arange(ph * ns) + 0.5) / (ph * ns)
        ix = (jnp.arange(pw * ns) + 0.5) / (pw * ns)

        def per_roi(ri):
            img = xa[img_of_roi[ri]]
            hh = jnp.maximum(y2[ri] - y1[ri], 1)
            ww = jnp.maximum(x2[ri] - x1[ri], 1)
            yy = jnp.clip(y1[ri] + iy * hh, 0, hgt - 1).astype(jnp.int32)
            xx = jnp.clip(x1[ri] + ix * ww, 0, wid - 1).astype(jnp.int32)
            patch = img[:, yy, :][:, :, xx]               # [C, ph*ns, pw*ns]
            return patch.reshape(c, ph, ns, pw, ns).max((2, 4))

        return jax.vmap(per_roi)(jnp.arange(ba.shape[0]))

    return apply(f, x_t, boxes_t, name="roi_pool")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None) -> Tensor:
    """Deformable convolution v1/v2 (reference: vision/ops.py deform_conv2d
    over deformable_conv kernels; v2 when mask is given).

    TPU design: deformable sampling = bilinear gather at offset positions,
    then the conv collapses to one big matmul over the sampled patches
    (im2col on the gathered taps) — the gather rides the VPU, the contraction
    the MXU."""
    x_t, off_t, w_t = as_tensor(x), as_tensor(offset), as_tensor(weight)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def f(xa, offa, wa, *rest):
        maska = rest[0] if mask is not None else None
        ba = rest[-1] if bias is not None else None
        n, c, hgt, wid = xa.shape
        co, ci_g, kh, kw = wa.shape
        out_h = (hgt + 2 * p[0] - dl[0] * (kh - 1) - 1) // s[0] + 1
        out_w = (wid + 2 * p[1] - dl[1] * (kw - 1) - 1) // s[1] + 1
        k = kh * kw

        # base sampling grid [out_h, out_w, k] in input coords
        oy = jnp.arange(out_h) * s[0] - p[0]
        ox = jnp.arange(out_w) * s[1] - p[1]
        ky = jnp.arange(kh) * dl[0]
        kx = jnp.arange(kw) * dl[1]
        base_y = oy[:, None, None] + ky[None, None, :].repeat(kw, -1) \
            .reshape(1, 1, k)
        base_x = ox[None, :, None] + jnp.tile(kx, kh).reshape(1, 1, k)

        # offsets: [N, 2*dg*k, H', W'], (y, x) interleaved per tap
        offa = offa.reshape(n, deformable_groups, k, 2, out_h, out_w)
        off_y = offa[:, :, :, 0].transpose(0, 1, 3, 4, 2)  # [N, dg, H', W', k]
        off_x = offa[:, :, :, 1].transpose(0, 1, 3, 4, 2)
        sy = base_y[None, None] + off_y
        sx = base_x[None, None] + off_x
        if maska is not None:
            m = maska.reshape(n, deformable_groups, k, out_h, out_w) \
                .transpose(0, 1, 3, 4, 2)
        else:
            m = jnp.ones_like(sy)

        cpg = c // deformable_groups  # channels per deformable group

        def sample_img(img, syi, sxi, mi):
            # img [cpg, H, W]; syi/sxi/mi [H', W', k] -> [cpg, H', W', k]
            # pointwise grid: every (y, x) pair is its own tap
            vy, y0, y1i, wy = _bilinear_axis(syi, hgt)
            vx, x0, x1i, wx = _bilinear_axis(sxi, wid)
            valid = vy & vx
            flat = img.reshape(cpg, -1)
            gidx = lambda yi, xi: jnp.take(flat, (yi * wid + xi).reshape(-1),
                                           axis=1).reshape(cpg, *yi.shape)
            val = (gidx(y0, x0) * ((1 - wy) * (1 - wx))[None] +
                   gidx(y0, x1i) * ((1 - wy) * wx)[None] +
                   gidx(y1i, x0) * (wy * (1 - wx))[None] +
                   gidx(y1i, x1i) * (wy * wx)[None])
            return val * (valid * mi)[None]

        def per_n(xi, syi, sxi, mi):
            # xi [c,H,W] split into dg groups
            xg = xi.reshape(deformable_groups, cpg, hgt, wid)
            cols = jax.vmap(sample_img)(xg, syi, sxi, mi)
            return cols.reshape(c, out_h, out_w, k)

        cols = jax.vmap(per_n)(xa, sy, sx, m)  # [N, C, H', W', K]
        # grouped conv as matmul: out[n,co,hw] = W[co, ci_g*k] @ cols
        cpg_conv = c // groups
        cols_g = cols.reshape(n, groups, cpg_conv, out_h * out_w, k)
        w_g = wa.reshape(groups, co // groups, ci_g, kh * kw)
        out = jnp.einsum("ngchk,gock->ngoh", cols_g, w_g,
                         preferred_element_type=jnp.float32)
        out = out.reshape(n, co, out_h, out_w).astype(xa.dtype)
        if ba is not None:
            out = out + ba.reshape(1, co, 1, 1)
        return out

    args = [x_t, off_t, w_t]
    if mask is not None:
        args.append(as_tensor(mask))
    if bias is not None:
        args.append(as_tensor(bias))
    return apply(f, *args, name="deform_conv2d")
