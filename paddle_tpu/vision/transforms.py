"""Vision transforms (reference: python/paddle/vision/transforms/).

Numpy-native: every transform consumes/produces HWC uint8/float numpy
arrays (or CHW float after ToTensor), so they run inside multiprocess
DataLoader workers with zero framework state.
"""

from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "BaseTransform",
           "RandomResizedCrop", "SaturationTransform", "ContrastTransform",
           "HueTransform", "ColorJitter", "RandomAffine", "RandomRotation",
           "RandomPerspective", "Grayscale", "RandomErasing", "to_tensor",
           "hflip", "vflip", "resize", "pad", "affine", "rotate",
           "perspective", "to_grayscale", "crop", "center_crop",
           "adjust_brightness", "adjust_contrast", "adjust_saturation",
           "adjust_hue", "normalize", "erase"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (numpy; DataLoader collate
    moves it to device)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        is_int = np.issubdtype(arr.dtype, np.integer)
        arr = arr.astype(np.float32)
        if is_int:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(arr, size):
    """Nearest+bilinear-free resize via index mapping (no PIL dependency)."""
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        # short side to `size`, keep aspect
        if h < w:
            nh, nw = size, max(int(round(w * size / h)), 1)
        else:
            nh, nw = max(int(round(h * size / w)), 1), size
    else:
        nh, nw = size
    ys = np.clip((np.arange(nh) + 0.5) * h / nh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(nw) + 0.5) * w / nw - 0.5, 0, w - 1)
    yi = np.round(ys).astype(np.int64)
    xi = np.round(xs).astype(np.int64)
    return arr[yi][:, xi]


class Resize:
    def __init__(self, size, interpolation="nearest"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def __call__(self, img):
        arr = np.asarray(img)
        th, tw = self.size
        h, w = arr.shape[:2]
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad, mode="constant")
        th, tw = self.size
        h, w = arr.shape[:2]
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        src_arr = np.asarray(img)
        # ceiling decided by the INPUT's dtype, not post-scale values;
        # dtype restored so chained transforms (ColorJitter) keep seeing
        # the convention their own ceiling logic expects
        ceil = 255.0 if np.issubdtype(src_arr.dtype, np.integer) else 1.0
        factor = max(0.0, 1 + pyrandom.uniform(-self.value, self.value))
        out = np.clip(src_arr.astype(np.float32) * factor, 0, ceil)
        return out.astype(src_arr.dtype)


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        elif len(p) == 2:  # (left/right, top/bottom), reference contract
            p = (p[0], p[1], p[0], p[1])
        pad = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pad, mode="constant",
                      constant_values=self.fill)


# -- r4b completion: the functional surface + remaining transform classes
# (reference: python/paddle/vision/transforms/{functional.py,transforms.py})


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def resize(img, size, interpolation="bilinear"):
    return _resize_np(np.asarray(img), size)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    p = padding
    if isinstance(p, numbers.Number):
        p = (p, p, p, p)
    elif len(p) == 2:
        p = (p[0], p[1], p[0], p[1])
    widths = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, widths, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(arr, widths, mode=mode)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img)
    ceil = 255.0 if np.issubdtype(arr.dtype, np.integer) else 1.0
    out = np.clip(arr.astype(np.float32) * brightness_factor, 0, ceil)
    return out.astype(arr.dtype)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img)
    ceil = 255.0 if np.issubdtype(arr.dtype, np.integer) else 1.0
    f = arr.astype(np.float32)
    gray_mean = f.mean() if f.ndim == 2 else \
        (f @ np.array([0.299, 0.587, 0.114], np.float32)).mean() \
        if f.shape[-1] == 3 else f.mean()
    out = np.clip(gray_mean + contrast_factor * (f - gray_mean), 0, ceil)
    return out.astype(arr.dtype)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img)
    ceil = 255.0 if np.issubdtype(arr.dtype, np.integer) else 1.0
    f = arr.astype(np.float32)
    gray = f @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.clip(gray[..., None] + saturation_factor
                  * (f - gray[..., None]), 0, ceil)
    return out.astype(arr.dtype)


def _rgb_to_hsv(f):
    mx = f.max(-1)
    mn = f.min(-1)
    d = mx - mn
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    h = np.zeros_like(mx)
    nz = d > 0
    idx = (mx == r) & nz
    h[idx] = ((g - b)[idx] / d[idx]) % 6
    idx = (mx == g) & nz & (mx != r)
    h[idx] = (b - r)[idx] / d[idx] + 2
    idx = (mx == b) & nz & (mx != r) & (mx != g)
    h[idx] = (r - g)[idx] / d[idx] + 4
    h = h / 6.0
    s = np.where(mx > 0, d / np.maximum(mx, 1e-12), 0.0)
    return h, s, mx


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    out = np.zeros(h.shape + (3,), np.float32)
    for k, (rr, gg, bb) in enumerate(((v, t, p), (q, v, p), (p, v, t),
                                      (p, q, v), (t, p, v), (v, p, q))):
        m = i == k
        out[m, 0] = rr[m]
        out[m, 1] = gg[m]
        out[m, 2] = bb[m]
    return out


def adjust_hue(img, hue_factor):
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img)
    is_int = np.issubdtype(arr.dtype, np.integer)
    f = arr.astype(np.float32) / (255.0 if is_int else 1.0)
    h, s, v = _rgb_to_hsv(f)
    h = (h + hue_factor) % 1.0
    out = _hsv_to_rgb(h, s, v)
    if is_int:
        return np.clip(out * 255.0, 0, 255).astype(arr.dtype)
    return out.astype(arr.dtype)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img)
    f = arr.astype(np.float32)
    gray = f @ np.array([0.299, 0.587, 0.114], np.float32) if \
        f.ndim == 3 and f.shape[-1] == 3 else f.reshape(f.shape[:2])
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return out.astype(arr.dtype)


def _is_chw(img, data_format=None):
    """CHW/HWC decision: explicit data_format wins; a Tensor is CHW and a
    PIL image HWC by type (the reference contract); only a bare ndarray —
    which this module's ToTensor emits as CHW — falls back to the shape
    heuristic."""
    if data_format is not None:
        return str(data_format).upper() == "CHW"
    from ..core.tensor import Tensor
    if isinstance(img, Tensor):
        return True
    if not isinstance(img, np.ndarray):  # PIL image
        return False
    return (img.ndim == 3 and img.shape[0] in (1, 3)
            and img.shape[-1] not in (1, 3))


def erase(img, i, j, h, w, v, inplace=False, data_format=None):
    chw = _is_chw(img, data_format)
    arr = np.asarray(img)
    out = arr if inplace else arr.copy()
    if out.ndim == 3 and chw:
        out[:, i:i + h, j:j + w] = v  # CHW
    else:
        out[i:i + h, j:j + w] = v     # HWC
    return out


def _inverse_warp(arr, inv_matrix, out_hw, fill=0):
    """Sample arr at inv_matrix @ (x_out, y_out, 1) — the shared engine
    for affine/rotate/perspective (nearest sampling, matching _resize_np's
    no-PIL policy)."""
    oh, ow = out_hw
    ys, xs = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype(np.float64)
    m = np.asarray(inv_matrix, np.float64).reshape(3, 3)
    src = m @ coords
    sx = src[0] / np.maximum(np.abs(src[2]), 1e-12) * np.sign(src[2])
    sy = src[1] / np.maximum(np.abs(src[2]), 1e-12) * np.sign(src[2])
    xi = np.round(sx).astype(np.int64)
    yi = np.round(sy).astype(np.int64)
    h, w = arr.shape[:2]
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    xi = np.clip(xi, 0, w - 1)
    yi = np.clip(yi, 0, h - 1)
    flat = arr[yi, xi]
    if arr.ndim == 3:
        flat = np.where(valid[:, None], flat, np.float64(fill)).astype(
            arr.dtype)
        return flat.reshape(oh, ow, arr.shape[2])
    flat = np.where(valid, flat, fill).astype(arr.dtype)
    return flat.reshape(oh, ow)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    # forward matrix: T(center) R S Shear T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0], [0, 0, 1.0]]) * 1.0
    m[:2, :2] *= scale
    m[0, 2] = cx + translate[0] - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = cy + translate[1] - m[1, 0] * cx - m[1, 1] * cy
    return m


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp (reference functional.py affine); inverse-mapped
    nearest sampling."""
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    # positive angle = counter-clockwise on the displayed image, same as
    # rotate(): the forward matrix takes -angle in y-down array coords,
    # and the sampler inverts it
    m = _affine_matrix(-angle, translate, scale, shear, center)
    return _inverse_warp(arr, np.linalg.inv(m), (h, w), fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by `angle` degrees (reference
    functional.py rotate)."""
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    # positive angle = counter-clockwise on the displayed image (y-down
    # array coords invert the usual math orientation)
    m = _affine_matrix(angle, (0, 0), 1.0, (0.0, 0.0), center)
    out_hw = (h, w)
    if expand:
        corners = np.array([[0, 0, 1], [w - 1, 0, 1], [0, h - 1, 1],
                            [w - 1, h - 1, 1]], np.float64).T
        mapped = np.linalg.inv(m) @ corners
        xs_, ys_ = mapped[0], mapped[1]
        nw = int(np.ceil(xs_.max() - xs_.min() + 1))
        nh = int(np.ceil(ys_.max() - ys_.min() + 1))
        shift = np.eye(3)
        shift[0, 2] = xs_.min()
        shift[1, 2] = ys_.min()
        m = m @ shift
        out_hw = (nh, nw)
    return _inverse_warp(arr, m, out_hw, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping startpoints -> endpoints (reference
    functional.py perspective): homography solved from the 4 pairs."""
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        bvec += [ex, ey]
    hvec = np.linalg.solve(np.asarray(a, np.float64),
                           np.asarray(bvec, np.float64))
    m = np.append(hvec, 1.0).reshape(3, 3)
    return _inverse_warp(arr, np.linalg.inv(m), (h, w), fill)


class BaseTransform:
    """Transform protocol (reference transforms.py BaseTransform):
    subclasses implement _apply_image (and optionally _get_params); keys
    select which inputs are images."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)
        self.params = None

    def _get_params(self, inputs):
        return None

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        single = not isinstance(inputs, (list, tuple))
        ins = (inputs,) if single else tuple(inputs)
        self.params = self._get_params(ins)
        outs = []
        for key, data in zip(self.keys, ins):
            outs.append(self._apply_image(data) if key == "image" else data)
        outs += list(ins[len(self.keys):])
        return outs[0] if single else tuple(outs)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("saturation value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        # reference sampling domain [max(0, 1-v), 1+v]: the factor never
        # goes negative (a negative factor would invert the image)
        return adjust_saturation(
            img, pyrandom.uniform(max(0.0, 1 - self.value), 1 + self.value))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_contrast(
            img, pyrandom.uniform(max(0.0, 1 - self.value), 1 + self.value))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.tfs = [BrightnessTransform(brightness),
                    ContrastTransform(contrast),
                    SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        pyrandom.shuffle(order)
        for k in order:
            t = self.tfs[k]
            img = t._apply_image(img) if isinstance(t, BaseTransform) \
                else t(img)
        return img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale, self.ratio = scale, ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = np.exp(pyrandom.uniform(np.log(self.ratio[0]),
                                         np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = pyrandom.randint(0, h - ch)
                j = pyrandom.randint(0, w - cw)
                return _resize_np(arr[i:i + ch, j:j + cw], self.size)
        return _resize_np(CenterCrop(min(h, w))(arr), self.size)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else tuple(degrees)
        self.translate, self.scale_rng = translate, scale
        self.shear, self.fill, self.center = shear, fill, center

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        angle = pyrandom.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = pyrandom.uniform(-self.translate[0], self.translate[0]) * w
            ty = pyrandom.uniform(-self.translate[1], self.translate[1]) * h
        sc = pyrandom.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (0.0, 0.0)
        if self.shear is not None:
            s = self.shear
            if isinstance(s, numbers.Number):
                sh = (pyrandom.uniform(-s, s), 0.0)
            elif len(s) == 2:          # x-shear range only
                sh = (pyrandom.uniform(s[0], s[1]), 0.0)
            elif len(s) == 4:          # (x_min, x_max, y_min, y_max)
                sh = (pyrandom.uniform(s[0], s[1]),
                      pyrandom.uniform(s[2], s[3]))
            else:
                raise ValueError(f"shear needs 1, 2 or 4 values, got {s}")
        return affine(arr, angle, (tx, ty), sc, sh, fill=self.fill,
                      center=self.center)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, numbers.Number) else tuple(degrees)
        self.expand, self.center, self.fill = expand, center, fill

    def _apply_image(self, img):
        return rotate(img, pyrandom.uniform(*self.degrees),
                      expand=self.expand, center=self.center,
                      fill=self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.distortion_scale, self.fill = (prob,
                                                       distortion_scale,
                                                       fill)

    def _apply_image(self, img):
        if pyrandom.random() >= self.prob:
            return np.asarray(img)
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        hw, hh = int(w * d / 2), int(h * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(pyrandom.randint(0, hw), pyrandom.randint(0, hh)),
               (w - 1 - pyrandom.randint(0, hw), pyrandom.randint(0, hh)),
               (w - 1 - pyrandom.randint(0, hw),
                h - 1 - pyrandom.randint(0, hh)),
               (pyrandom.randint(0, hw), h - 1 - pyrandom.randint(0, hh))]
        return perspective(arr, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        chw = _is_chw(img)
        arr = np.asarray(img)
        if pyrandom.random() >= self.prob:
            return arr
        chw = chw and arr.ndim == 3
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = np.exp(pyrandom.uniform(np.log(self.ratio[0]),
                                         np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = pyrandom.randint(0, h - eh)
                j = pyrandom.randint(0, w - ew)
                return erase(arr, i, j, eh, ew, self.value, self.inplace,
                             data_format="CHW" if chw else "HWC")
        return arr
