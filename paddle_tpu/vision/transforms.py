"""Vision transforms (reference: python/paddle/vision/transforms/).

Numpy-native: every transform consumes/produces HWC uint8/float numpy
arrays (or CHW float after ToTensor), so they run inside multiprocess
DataLoader workers with zero framework state.
"""

from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (numpy; DataLoader collate
    moves it to device)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        is_int = np.issubdtype(arr.dtype, np.integer)
        arr = arr.astype(np.float32)
        if is_int:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(arr, size):
    """Nearest+bilinear-free resize via index mapping (no PIL dependency)."""
    h, w = arr.shape[:2]
    if isinstance(size, numbers.Number):
        # short side to `size`, keep aspect
        if h < w:
            nh, nw = size, max(int(round(w * size / h)), 1)
        else:
            nh, nw = max(int(round(h * size / w)), 1), size
    else:
        nh, nw = size
    ys = np.clip((np.arange(nh) + 0.5) * h / nh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(nw) + 0.5) * w / nw - 0.5, 0, w - 1)
    yi = np.round(ys).astype(np.int64)
    xi = np.round(xs).astype(np.int64)
    return arr[yi][:, xi]


class Resize:
    def __init__(self, size, interpolation="nearest"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def __call__(self, img):
        arr = np.asarray(img)
        th, tw = self.size
        h, w = arr.shape[:2]
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad, mode="constant")
        th, tw = self.size
        h, w = arr.shape[:2]
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if pyrandom.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        src_arr = np.asarray(img)
        # ceiling decided by the INPUT's dtype, not post-scale values
        ceil = 255.0 if np.issubdtype(src_arr.dtype, np.integer) else 1.0
        factor = 1 + pyrandom.uniform(-self.value, self.value)
        return np.clip(src_arr.astype(np.float32) * factor, 0, ceil)


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        elif len(p) == 2:  # (left/right, top/bottom), reference contract
            p = (p[0], p[1], p[0], p[1])
        pad = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pad, mode="constant",
                      constant_values=self.fill)
