"""Vision datasets (reference: python/paddle/vision/datasets/).

No-network environment: MNIST/Cifar parse already-downloaded files;
DatasetFolder walks a class-per-directory tree with a pluggable loader.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder"]


class MNIST(Dataset):
    """idx-format MNIST from local files (reference datasets/mnist.py; the
    download step is out of scope in an egress-less environment — pass
    image_path/label_path to the extracted/gz files)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, backend="cv2"):
        if image_path is None or label_path is None:
            raise ValueError("MNIST needs explicit image_path/label_path "
                             "(no network download available)")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return np.frombuffer(f.read(n), dtype=np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from a local python-version tarball (reference
    datasets/cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend="cv2"):
        if data_file is None:
            raise ValueError("Cifar10 needs data_file (no network download)")
        self.transform = transform
        wanted = self._members(mode)
        xs, ys = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if any(w in m.name for w in wanted):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"]))
                    ys.extend(d[self._label_key])
        if not xs:
            raise ValueError(
                f"no {wanted} members found in {data_file}; wrong archive "
                f"for {type(self).__name__}?")
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        self.labels = np.asarray(ys, dtype=np.int64)

    _label_key = b"labels"

    @staticmethod
    def _members(mode):
        return ["data_batch"] if mode == "train" else ["test_batch"]

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class Cifar100(Cifar10):
    """CIFAR-100 python tarball: members cifar-100-python/{train,test},
    labels under b'fine_labels' (reference datasets/cifar.py mode100)."""

    _label_key = b"fine_labels"

    @staticmethod
    def _members(mode):
        return ["/train"] if mode == "train" else ["/test"]


class DatasetFolder(Dataset):
    """class-per-subdirectory dataset (reference datasets/folder.py).
    Default loader reads .npy; pass `loader` for image decoding."""

    def __init__(self, root, loader=None, extensions=(".npy",),
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or (lambda p: np.load(p))
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(tuple(extensions))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        path, target = self.samples[i]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.int64(target)
