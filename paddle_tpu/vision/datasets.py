"""Vision datasets (reference: python/paddle/vision/datasets/).

No-network environment: MNIST/Cifar parse already-downloaded files;
DatasetFolder walks a class-per-directory tree with a pluggable loader.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder"]


class MNIST(Dataset):
    """idx-format MNIST from local files (reference datasets/mnist.py; the
    download step is out of scope in an egress-less environment — pass
    image_path/label_path to the extracted/gz files)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, backend="cv2"):
        if image_path is None or label_path is None:
            raise ValueError("MNIST needs explicit image_path/label_path "
                             "(no network download available)")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return np.frombuffer(f.read(n), dtype=np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from a local python-version tarball (reference
    datasets/cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend="cv2"):
        if data_file is None:
            raise ValueError("Cifar10 needs data_file (no network download)")
        self.transform = transform
        wanted = self._members(mode)
        xs, ys = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if any(w in m.name for w in wanted):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"]))
                    ys.extend(d[self._label_key])
        if not xs:
            raise ValueError(
                f"no {wanted} members found in {data_file}; wrong archive "
                f"for {type(self).__name__}?")
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        self.labels = np.asarray(ys, dtype=np.int64)

    _label_key = b"labels"

    @staticmethod
    def _members(mode):
        return ["data_batch"] if mode == "train" else ["test_batch"]

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class Cifar100(Cifar10):
    """CIFAR-100 python tarball: members cifar-100-python/{train,test},
    labels under b'fine_labels' (reference datasets/cifar.py mode100)."""

    _label_key = b"fine_labels"

    @staticmethod
    def _members(mode):
        return ["/train"] if mode == "train" else ["/test"]


class DatasetFolder(Dataset):
    """class-per-subdirectory dataset (reference datasets/folder.py).
    Default loader reads .npy; pass `loader` for image decoding."""

    def __init__(self, root, loader=None, extensions=(".npy",),
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or (lambda p: np.load(p))
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(tuple(extensions))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        path, target = self.samples[i]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.int64(target)


class ImageFolder(Dataset):
    """Flat folder of images (reference datasets/folder.py ImageFolder):
    every file under root that matches `extensions` (or passes
    is_valid_file) is one unlabeled sample."""

    _EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
             ".tiff", ".webp")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if loader is None:
            def loader(path):
                from PIL import Image
                with open(path, "rb") as f:
                    return Image.open(f).convert("RGB")
        self.loader = loader
        exts = tuple(e.lower() for e in (extensions or self._EXTS))
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(exts)
        samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(dirpath, fn)
                if is_valid_file(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in {root} with supported extensions")
        self.samples = samples

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


class Flowers(Dataset):
    """Oxford 102 Flowers from local archives (reference
    datasets/flowers.py; no network: pass data_file/label_file/setid_file
    to the .tgz / .mat files)."""

    _FLAGS = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        if not (data_file and label_file and setid_file):
            raise ValueError(
                "Flowers needs explicit data_file/label_file/setid_file "
                "(no network download available)")
        backend = backend or "pil"
        if backend not in ("pil", "cv2"):
            raise ValueError(
                f"Expected backend are one of ['pil', 'cv2'], but got "
                f"{backend}")
        self.backend = backend
        self.transform = transform
        flag = self._FLAGS[mode.lower()]
        import scipy.io as scio
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[flag][0]
        self._tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]]).astype("int64")
        raw = self._tar.extractfile(
            self._members["jpg/image_%05d.jpg" % index]).read()
        image = Image.open(_io.BytesIO(raw))
        if self.backend == "cv2":
            image = np.array(image)
        if self.transform is not None:
            image = self.transform(image)
        if self.backend == "cv2":
            return np.asarray(image, np.float32), label
        return image, label


class VOC2012(Dataset):
    """VOC2012 segmentation pairs from the local tar (reference
    datasets/voc2012.py)."""

    _FLAGS = {"train": "trainval", "test": "train", "valid": "val"}
    _SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _DATA = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LABEL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if not data_file:
            raise ValueError("VOC2012 needs an explicit data_file "
                             "(no network download available)")
        backend = backend or "pil"
        if backend not in ("pil", "cv2"):
            raise ValueError(
                f"Expected backend are one of ['pil', 'cv2'], but got "
                f"{backend}")
        self.backend = backend
        self.transform = transform
        flag = self._FLAGS[mode.lower()]
        self._tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}
        sets = self._tar.extractfile(self._members[self._SET.format(flag)])
        self.data, self.labels = [], []
        for line in sets:
            name = line.strip().decode("utf-8")
            self.data.append(self._DATA.format(name))
            self.labels.append(self._LABEL.format(name))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        data = Image.open(_io.BytesIO(self._tar.extractfile(
            self._members[self.data[idx]]).read()))
        label = Image.open(_io.BytesIO(self._tar.extractfile(
            self._members[self.labels[idx]]).read()))
        if self.backend == "cv2":
            data, label = np.array(data), np.array(label)
        if self.transform is not None:
            data = self.transform(data)
        if self.backend == "cv2":
            return data.astype(np.float32), label.astype(np.float32)
        return data, label


__all__ += ["ImageFolder", "Flowers", "VOC2012"]
