"""Detection op family (reference: python/paddle/vision/ops.py — yolo_loss
:51, yolo_box :259, prior_box :420, box_coder :566, distribute_fpn_proposals
:1149, read_file :1294, decode_jpeg :1336, psroi_pool :1385, generate_proposals
:2028, matrix_nms :2205; kernels under paddle/phi/kernels/cpu/).

TPU design split:
- dense, static-shape compute (yolo_loss, yolo_box, prior_box, box_coder,
  psroi_pool) is fully vectorized jnp — jittable, differentiable where the
  reference is, rides the VPU/MXU;
- dynamic-output post-processing (matrix_nms, generate_proposals,
  distribute_fpn_proposals) runs on host in numpy, exactly like the
  reference's CPU-only detection kernels — these are eager, after-the-model
  ops whose output shapes depend on the data.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..autograd.function import apply, apply_multi
from ..core.tensor import Tensor, as_tensor

__all__ = [
    "yolo_loss", "yolo_box", "prior_box", "box_coder", "matrix_nms",
    "generate_proposals", "distribute_fpn_proposals", "psroi_pool",
    "read_file", "decode_jpeg", "DeformConv2D", "RoIAlign", "RoIPool",
    "PSRoIPool",
]


def _sce(x, label):
    """Numerically-stable sigmoid cross entropy (reference
    yolo_loss_kernel.cc SigmoidCrossEntropy)."""
    return jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _cwh_iou(x1, y1, w1, h1, x2, y2, w2, h2):
    """IoU of center/size boxes with broadcasting (CalcBoxIoU)."""
    ov_w = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2) - \
        jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
    ov_h = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2) - \
        jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
    inter = jnp.where((ov_w < 0) | (ov_h < 0), 0.0, ov_w * ov_h)
    union = w1 * h1 + w2 * h2 - inter
    return inter / jnp.maximum(union, 1e-10)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference vision/ops.py:51 over
    phi/kernels/cpu/yolo_loss_kernel.cc). Returns per-sample loss [N].

    Fully vectorized: the per-cell ignore mask is a broadcast IoU against
    all gt boxes; positive-sample assignment scatters per-gt targets into
    the grid. Differentiable w.r.t. x."""
    anchors = [int(a) for a in anchors]
    anchor_mask = [int(m) for m in anchor_mask]
    class_num = int(class_num)
    s_num = len(anchor_mask)
    a_num = len(anchors) // 2
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    x_t, gtb_t, gtl_t = as_tensor(x), as_tensor(gt_box), as_tensor(gt_label)
    args = [x_t, gtb_t, gtl_t]
    if gt_score is not None:
        args.append(as_tensor(gt_score))

    n, c, h, w = (int(d) for d in x_t.shape)
    b = int(gtb_t.shape[1])
    input_size = downsample_ratio * h
    aw = jnp.asarray([anchors[2 * i] for i in range(a_num)], jnp.float32)
    ah = jnp.asarray([anchors[2 * i + 1] for i in range(a_num)], jnp.float32)
    # all-anchor index -> position inside anchor_mask (or -1)
    mask_of = np.full(a_num, -1, np.int32)
    for pos, an in enumerate(anchor_mask):
        mask_of[an] = pos
    mask_of = jnp.asarray(mask_of)

    if use_label_smooth:
        smooth = min(1.0 / class_num, 1.0 / 40)
        pos_l, neg_l = 1.0 - smooth, smooth
    else:
        pos_l, neg_l = 1.0, 0.0

    def f(xa, gtb, gtl, *rest):
        score = rest[0] if rest else jnp.ones((n, b), xa.dtype)
        xr = xa.reshape(n, s_num, 5 + class_num, h, w)
        gx, gy = gtb[..., 0], gtb[..., 1]          # [N, B] normalized
        gw, gh = gtb[..., 2], gtb[..., 3]
        valid = (gw >= 1e-6) & (gh >= 1e-6)

        # --- per-cell ignore mask: best IoU of the predicted box vs gts
        grid_x = jnp.arange(w, dtype=xa.dtype)
        grid_y = jnp.arange(h, dtype=xa.dtype)
        sig = jnp.asarray(1.0, xa.dtype) / (1.0 + jnp.exp(-xr[:, :, 0]))
        px = (grid_x[None, None, None, :]
              + sig * scale + bias) / w            # [N, S, H, W]
        sig_y = 1.0 / (1.0 + jnp.exp(-xr[:, :, 1]))
        py = (grid_y[None, None, :, None] + sig_y * scale + bias) / h
        maw = aw[jnp.asarray(anchor_mask)]
        mah = ah[jnp.asarray(anchor_mask)]
        pw = jnp.exp(xr[:, :, 2]) * maw[None, :, None, None] / input_size
        ph = jnp.exp(xr[:, :, 3]) * mah[None, :, None, None] / input_size
        iou_all = _cwh_iou(
            px[..., None], py[..., None], pw[..., None], ph[..., None],
            gx[:, None, None, None, :], gy[:, None, None, None, :],
            gw[:, None, None, None, :], gh[:, None, None, None, :])
        iou_all = jnp.where(valid[:, None, None, None, :], iou_all, 0.0)
        best_iou = jnp.max(iou_all, axis=-1) if b else \
            jnp.zeros_like(px)                    # [N, S, H, W]
        obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)

        # --- positive assignment: best anchor per gt over ALL anchors
        an_iou = _cwh_iou(
            jnp.zeros(()), jnp.zeros(()),
            (aw / input_size)[None, None, :], (ah / input_size)[None, None, :],
            jnp.zeros(()), jnp.zeros(()), gw[..., None], gh[..., None])
        best_n = jnp.argmax(an_iou, axis=-1)       # [N, B]
        midx = mask_of[best_n]                     # [N, B] (-1 = unmatched)
        is_pos = valid & (midx >= 0)
        gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)
        mc = jnp.clip(midx, 0, s_num - 1)

        # positives overwrite the per-cell obj mask with their mixup score.
        # The kernel iterates gts in order (last gt wins on a shared cell):
        # reproduce that deterministically by electing max-t per cell first,
        # then writing the winner's score — duplicate-index .at[].set order
        # is unspecified in JAX.
        n_idx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, b))
        flat_cell = ((n_idx * s_num + mc) * h + gj) * w + gi
        ncell = n * s_num * h * w
        flat_cell = jnp.where(is_pos, flat_cell, ncell)
        t_idx = jnp.broadcast_to(jnp.arange(b)[None, :], (n, b))
        winner = jnp.full((ncell + 1,), -1, jnp.int32).at[
            flat_cell.reshape(-1)].max(t_idx.reshape(-1).astype(jnp.int32))
        winner = winner[:-1]                        # [ncell]
        n_of_cell = jnp.arange(ncell) // (s_num * h * w)
        win_score = score[n_of_cell, jnp.clip(winner, 0, b - 1)]
        obj_flat = jnp.where(winner >= 0, win_score, obj_mask.reshape(-1))
        obj_mask = obj_flat.reshape(n, s_num, h, w)

        # --- location + class loss per gt (additive over gts, like the
        # kernel's per-gt loop)
        pred_at = xr[n_idx, mc, :, gj, gi]         # [N, B, 5+C]
        tx = gx * w - gi.astype(xa.dtype)
        ty = gy * h - gj.astype(xa.dtype)
        tw = jnp.log(jnp.where(is_pos, gw * input_size / aw[best_n], 1.0))
        th = jnp.log(jnp.where(is_pos, gh * input_size / ah[best_n], 1.0))
        loc_scale = (2.0 - gw * gh) * score
        loc = (_sce(pred_at[..., 0], tx) + _sce(pred_at[..., 1], ty)
               + jnp.abs(pred_at[..., 2] - tw)
               + jnp.abs(pred_at[..., 3] - th)) * loc_scale
        cls_target = jnp.where(
            jnp.arange(class_num)[None, None, :] == gtl[..., None], pos_l,
            neg_l).astype(xa.dtype)
        cls = jnp.sum(_sce(pred_at[..., 5:], cls_target), -1) * score
        per_gt = jnp.where(is_pos, loc + cls, 0.0)

        # --- objectness loss over every cell
        pobj = xr[:, :, 4]
        obj_loss = jnp.where(
            obj_mask > 1e-5, _sce(pobj, 1.0) * obj_mask,
            jnp.where(obj_mask > -0.5, _sce(pobj, 0.0), 0.0))
        return per_gt.sum(-1) + obj_loss.sum((1, 2, 3))

    return apply(f, *args, name="yolo_loss")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """YOLOv3 box decode (reference vision/ops.py:259 over
    phi/kernels/cpu/yolo_box_kernel.cc + funcs/yolo_box_util.h).
    Returns (boxes [N, A*H*W, 4], scores [N, A*H*W, class_num])."""
    anchors = [int(a) for a in anchors]
    a_num = len(anchors) // 2
    class_num = int(class_num)
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)
    x_t, img_t = as_tensor(x), as_tensor(img_size)
    n, c, h, w = (int(d) for d in x_t.shape)
    in_h, in_w = downsample_ratio * h, downsample_ratio * w
    aw = jnp.asarray([anchors[2 * i] for i in range(a_num)], jnp.float32)
    ah = jnp.asarray([anchors[2 * i + 1] for i in range(a_num)], jnp.float32)

    def f(xa, img):
        if iou_aware:
            iou_pred = xa[:, :a_num].reshape(n, a_num, h, w)
            body = xa[:, a_num:].reshape(n, a_num, 5 + class_num, h, w)
        else:
            iou_pred = None
            body = xa.reshape(n, a_num, 5 + class_num, h, w)
        img_h = img[:, 0].astype(xa.dtype)[:, None, None, None]
        img_w = img[:, 1].astype(xa.dtype)[:, None, None, None]
        sig = lambda v: 1.0 / (1.0 + jnp.exp(-v))  # noqa: E731
        cx = (jnp.arange(w, dtype=xa.dtype)[None, None, None, :]
              + sig(body[:, :, 0]) * scale + bias) * img_w / w
        cy = (jnp.arange(h, dtype=xa.dtype)[None, None, :, None]
              + sig(body[:, :, 1]) * scale + bias) * img_h / h
        bw = jnp.exp(body[:, :, 2]) * aw[None, :, None, None] * img_w / in_w
        bh = jnp.exp(body[:, :, 3]) * ah[None, :, None, None] * img_h / in_h
        conf = sig(body[:, :, 4])
        if iou_pred is not None:
            iou = sig(iou_pred)
            conf = conf ** (1.0 - iou_aware_factor) * iou ** iou_aware_factor
        keep = conf >= conf_thresh

        x1, y1 = cx - bw / 2, cy - bh / 2
        x2, y2 = cx + bw / 2, cy + bh / 2
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0)
            y1 = jnp.clip(y1, 0.0)
            x2 = jnp.minimum(x2, img_w - 1)
            y2 = jnp.minimum(y2, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
        scores = sig(body[:, :, 5:]) * conf[:, :, None]
        scores = scores * keep[:, :, None]
        boxes = boxes.reshape(n, a_num * h * w, 4)
        scores = jnp.moveaxis(scores, 2, -1).reshape(
            n, a_num * h * w, class_num)
        return boxes, scores

    return apply_multi(f, x_t, img_t, name="yolo_box")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference vision/ops.py:420 over
    phi/kernels/cpu/prior_box_kernel.cc). Returns (boxes, variances), each
    [H, W, num_priors, 4]; the grid is static so this builds both as
    constants the compiler folds."""
    def listify(v):
        return [float(x) for x in (v if isinstance(v, (list, tuple)) else [v])]

    min_sizes = listify(min_sizes)
    aspect_ratios = listify(aspect_ratios)
    steps = listify(steps)
    if len(steps) != 2:
        raise ValueError("steps should be (step_w, step_h)")
    max_sizes = listify(max_sizes) if max_sizes else []
    if max_sizes and not (len(max_sizes) and max_sizes[0] > 0):
        max_sizes = []

    # ExpandAspectRatios: dedup, always lead with 1.0, optional flip
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - e) >= 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    in_t, img_t = as_tensor(input), as_tensor(image)
    fh, fw = int(in_t.shape[2]), int(in_t.shape[3])
    ih, iw = int(img_t.shape[2]), int(img_t.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    boxes = np.zeros((fh, fw, 0, 4), np.float32)
    cx = (np.arange(fw) + offset) * step_w          # [fw]
    cy = (np.arange(fh) + offset) * step_h          # [fh]
    cxg, cyg = np.meshgrid(cx, cy)                  # [fh, fw]

    def emit(bw, bh):
        bx = np.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], -1)
        return bx[:, :, None, :]

    per_pos = []
    for s, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            per_pos.append(emit(ms / 2.0, ms / 2.0))
            if max_sizes:
                mx = np.sqrt(ms * max_sizes[s]) / 2.0
                per_pos.append(emit(mx, mx))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                per_pos.append(emit(ms * np.sqrt(ar) / 2, ms / np.sqrt(ar) / 2))
        else:
            for ar in ars:
                per_pos.append(emit(ms * np.sqrt(ar) / 2, ms / np.sqrt(ar) / 2))
            if max_sizes:
                mx = np.sqrt(ms * max_sizes[s]) / 2.0
                per_pos.append(emit(mx, mx))
    boxes = np.concatenate(per_pos, 2).astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    num_priors = boxes.shape[2]
    vars_ = np.broadcast_to(
        np.asarray(variance, np.float32), (fh, fw, num_priors, 4)).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(vars_))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (reference vision/ops.py:566 over
    phi/kernels/cpu/box_coder_kernel.cc)."""
    if code_type not in ("encode_center_size", "decode_center_size"):
        raise ValueError(
            "code_type must be encode_center_size or decode_center_size, "
            f"got {code_type}")
    pb = as_tensor(prior_box)
    tb = as_tensor(target_box)
    norm_off = 0.0 if box_normalized else 1.0
    var_t = None
    var_const = None
    if prior_box_var is None:
        pass
    elif isinstance(prior_box_var, (list, tuple)):
        if len(prior_box_var) != 4:
            raise ValueError("prior_box_var list must have 4 elements")
        var_const = np.asarray(prior_box_var, np.float32)
    else:
        var_t = as_tensor(prior_box_var)

    def _prior_cwh(p):
        w = p[:, 2] - p[:, 0] + norm_off
        h = p[:, 3] - p[:, 1] + norm_off
        return p[:, 0] + w / 2, p[:, 1] + h / 2, w, h

    if code_type == "encode_center_size":
        def f(p, t, *rest):
            pcx, pcy, pw, ph = _prior_cwh(p)       # [col]
            tcx = (t[:, 2] + t[:, 0]) / 2          # [row]
            tcy = (t[:, 3] + t[:, 1]) / 2
            tw = t[:, 2] - t[:, 0] + norm_off
            th = t[:, 3] - t[:, 1] + norm_off
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], -1)  # [row, col, 4]
            if rest:
                out = out / rest[0][None, :, :]
            elif var_const is not None:
                out = out / jnp.asarray(var_const)
            return out

        args = (pb, tb) + ((var_t,) if var_t is not None else ())
        return apply(f, *args, name="box_coder")

    def f(p, t, *rest):
        pcx, pcy, pw, ph = _prior_cwh(p)
        # axis=0: priors broadcast over rows; axis=1: over cols
        ex = (lambda v: v[None, :]) if axis == 0 else (lambda v: v[:, None])
        if rest:
            var = ex(rest[0]) if axis == 0 else rest[0][:, None, :]
            vx, vy, vw, vh = (var[..., k] for k in range(4))
        elif var_const is not None:
            vx, vy, vw, vh = (float(var_const[k]) for k in range(4))
        else:
            vx = vy = vw = vh = 1.0
        tcx = vx * t[..., 0] * ex(pw) + ex(pcx)
        tcy = vy * t[..., 1] * ex(ph) + ex(pcy)
        tw = jnp.exp(vw * t[..., 2]) * ex(pw)
        th = jnp.exp(vh * t[..., 3]) * ex(ph)
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - norm_off,
                          tcy + th / 2 - norm_off], -1)

    args = (pb, tb) + ((var_t,) if var_t is not None else ())
    return apply(f, *args, name="box_coder")


# --- host-side dynamic-output post-processing ------------------------------


def _np_iou(a, b, normalized):
    """Pairwise IoU of corner boxes (JaccardOverlap semantics: +1 extent
    for unnormalized pixel boxes, invalid boxes have zero area)."""
    off = 0.0 if normalized else 1.0

    def area(bx):
        w = bx[:, 2] - bx[:, 0] + off
        h = bx[:, 3] - bx[:, 1] + off
        bad = (bx[:, 2] < bx[:, 0]) | (bx[:, 3] < bx[:, 1])
        return np.where(bad, 0.0, w * h)

    ix = np.minimum(a[:, None, 2], b[None, :, 2]) - \
        np.maximum(a[:, None, 0], b[None, :, 0]) + off
    iy = np.minimum(a[:, None, 3], b[None, :, 3]) - \
        np.maximum(a[:, None, 1], b[None, :, 1]) + off
    inter = np.clip(ix, 0, None) * np.clip(iy, 0, None)
    sep = (b[None, :, 0] > a[:, None, 2]) | (b[None, :, 2] < a[:, None, 0]) \
        | (b[None, :, 1] > a[:, None, 3]) | (b[None, :, 3] < a[:, None, 1])
    inter = np.where(sep, 0.0, inter)
    union = area(a)[:, None] + area(b)[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def _matrix_nms_single(boxes, scores, score_threshold, post_threshold,
                       nms_top_k, use_gaussian, sigma, normalized):
    """One class, one image (NMSMatrix): decayed scores + kept indices."""
    idx = np.where(scores > score_threshold)[0]
    if idx.size == 0:
        return np.empty(0, np.int64), np.empty(0, np.float64)
    order = idx[np.argsort(-scores[idx], kind="stable")]
    if nms_top_k > -1 and order.size > nms_top_k:
        order = order[:nms_top_k]
    sel = boxes[order]
    iou = _np_iou(sel, sel, normalized)
    m = order.size
    tri = np.tril(np.ones((m, m), bool), -1)       # j < i
    # iou_max[j] = max_{k<j} iou[j,k] (NMSMatrix's running per-row max)
    iou_max = np.zeros(m)
    if m > 1:
        iou_max[1:] = np.max(np.where(tri, iou, 0.0), axis=1)[1:]
    if use_gaussian:
        decay = np.exp((iou_max[None, :] ** 2 - iou ** 2) * sigma)
    else:
        decay = (1.0 - iou) / (1.0 - iou_max[None, :])
    decay = np.where(tri, decay, 1.0)
    min_decay = np.min(decay, axis=1)
    ds = min_decay * scores[order]
    keep = ds > post_threshold
    return order[keep], ds[keep]


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py:2205 over
    phi/kernels/cpu/matrix_nms_kernel.cc). Host-side: output count is
    data-dependent. Returns (out [No, 6], rois_num, index) following the
    reference's (Out, RoisNum, Index) ordering."""
    bb = np.asarray(as_tensor(bboxes).numpy(), np.float64)
    sc = np.asarray(as_tensor(scores).numpy(), np.float64)
    batch, cls_num, nbox = sc.shape
    outs, idxs, per_batch = [], [], []
    for i in range(batch):
        all_idx, all_sc, all_cls = [], [], []
        for c in range(cls_num):
            if c == background_label:
                continue
            ki, ks = _matrix_nms_single(
                bb[i], sc[i, c], score_threshold, post_threshold, nms_top_k,
                use_gaussian, gaussian_sigma, normalized)
            all_idx.append(ki)
            all_sc.append(ks)
            all_cls.append(np.full(ki.shape, c, np.float64))
        all_idx = np.concatenate(all_idx) if all_idx else np.empty(0, np.int64)
        all_sc = np.concatenate(all_sc) if all_sc else np.empty(0)
        all_cls = np.concatenate(all_cls) if all_cls else np.empty(0)
        num = all_idx.size
        if keep_top_k > -1:
            num = min(num, keep_top_k)
        order = np.argsort(-all_sc, kind="stable")[:num]
        det = np.stack([all_cls[order], all_sc[order]], -1)
        det = np.concatenate([det, bb[i][all_idx[order]]], -1) if num else \
            np.zeros((0, 2 + bb.shape[-1]))
        outs.append(det)
        idxs.append(i * nbox + all_idx[order])
        per_batch.append(num)
    out = np.concatenate(outs, 0).astype(np.float32) if outs else \
        np.zeros((0, 6), np.float32)
    index = np.concatenate(idxs, 0).astype(np.int32).reshape(-1, 1)
    rois_num = np.asarray(per_batch, np.int32)
    out_t = Tensor(jnp.asarray(out))
    idx_t = Tensor(jnp.asarray(index)) if return_index else None
    num_t = Tensor(jnp.asarray(rois_num)) if return_rois_num else None
    return out_t, num_t, idx_t


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference vision/ops.py:2028 over
    phi/kernels/cpu/generate_proposals_kernel.cc). Host-side (dynamic
    output). Returns (rois [M,4], roi_probs [M,1][, rois_num])."""
    sc = np.asarray(as_tensor(scores).numpy(), np.float64)    # [N, A, H, W]
    bd = np.asarray(as_tensor(bbox_deltas).numpy(), np.float64)  # [N,4A,H,W]
    im = np.asarray(as_tensor(img_size).numpy(), np.float64)  # [N, 2] (h, w)
    an = np.asarray(as_tensor(anchors).numpy(), np.float64).reshape(-1, 4)
    va = np.asarray(as_tensor(variances).numpy(), np.float64).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_probs, nums = [], [], []
    for i in range(n):
        # layout: anchors are [H, W, A, 4]; flatten scores/deltas to match
        s_i = sc[i].transpose(1, 2, 0).reshape(-1)            # [H*W*A]
        d_i = bd[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s_i, kind="stable")
        if pre_nms_top_n > 0 and order.size > pre_nms_top_n:
            order = order[:pre_nms_top_n]
        anc, var, dlt = an[order], va[order], d_i[order]
        aw = anc[:, 2] - anc[:, 0] + off
        ah_ = anc[:, 3] - anc[:, 1] + off
        acx, acy = anc[:, 0] + aw / 2, anc[:, 1] + ah_ / 2
        cx = var[:, 0] * dlt[:, 0] * aw + acx
        cy = var[:, 1] * dlt[:, 1] * ah_ + acy
        bw = np.exp(np.minimum(var[:, 2] * dlt[:, 2], 15.0)) * aw
        bh = np.exp(np.minimum(var[:, 3] * dlt[:, 3], 15.0)) * ah_
        props = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], -1)
        ih, iw = im[i, 0], im[i, 1]
        props[:, 0] = np.clip(props[:, 0], 0, iw - off)
        props[:, 1] = np.clip(props[:, 1], 0, ih - off)
        props[:, 2] = np.clip(props[:, 2], 0, iw - off)
        props[:, 3] = np.clip(props[:, 3], 0, ih - off)
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        ms = max(min_size, 1.0)
        if pixel_offset:
            cx_in = (props[:, 0] + props[:, 2]) / 2
            cy_in = (props[:, 1] + props[:, 3]) / 2
            keep = (ws >= ms) & (hs >= ms) & (cx_in < iw) & (cy_in < ih)
        else:
            keep = (ws >= ms) & (hs >= ms)
        props, probs = props[keep], s_i[order][keep]
        if len(props) == 0:
            # reference ProposalForOneImage: an image with nothing left
            # emits one all-zero proposal so rois_num is never 0
            props = np.zeros((1, 4))
            probs = np.zeros((1,))
        elif nms_thresh > 0:
            # greedy NMS (adaptive eta); nms_thresh <= 0 skips NMS AND the
            # post_nms cap, like the kernel's early return
            sel = []
            thresh = nms_thresh
            cand = list(range(len(props)))
            iou = _np_iou(props, props, not pixel_offset)
            while cand:
                cur = cand[0]
                sel.append(cur)
                if post_nms_top_n > 0 and len(sel) >= post_nms_top_n:
                    break
                cand = [j for j in cand[1:] if iou[cur, j] <= thresh]
                if eta < 1.0 and thresh > 0.5:
                    thresh *= eta
            props, probs = props[sel], probs[sel]
        all_rois.append(props)
        all_probs.append(probs)
        nums.append(len(props))
    rois = np.concatenate(all_rois, 0).astype(np.float32) if all_rois else \
        np.zeros((0, 4), np.float32)
    probs = np.concatenate(all_probs, 0).astype(np.float32).reshape(-1, 1)
    rois_t = Tensor(jnp.asarray(rois))
    probs_t = Tensor(jnp.asarray(probs))
    if return_rois_num:
        return rois_t, probs_t, Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    return rois_t, probs_t


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Distribute RoIs across FPN levels (reference vision/ops.py:1149 over
    phi/kernels/cpu/distribute_fpn_proposals_kernel.cc). Host-side."""
    assert max_level > min_level > 0
    rois = np.asarray(as_tensor(fpn_rois).numpy(), np.float64)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(ws * hs, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    num_lvl = max_level - min_level + 1
    multi_rois, restore_parts, lvl_nums = [], [], []
    bn = None
    if rois_num is not None:
        bn = np.asarray(as_tensor(rois_num).numpy(), np.int64)
        img_of = np.repeat(np.arange(len(bn)), bn)
    for k in range(num_lvl):
        pick = np.where(lvl == min_level + k)[0]
        multi_rois.append(Tensor(jnp.asarray(
            rois[pick].astype(np.float32))))
        restore_parts.append(pick)
        if bn is not None:
            lvl_nums.append(Tensor(jnp.asarray(np.bincount(
                img_of[pick], minlength=len(bn)).astype(np.int32))))
    order = np.concatenate(restore_parts) if restore_parts else \
        np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    restore_t = Tensor(jnp.asarray(restore.astype(np.int32).reshape(-1, 1)))
    if rois_num is not None:
        return multi_rois, restore_t, lvl_nums
    return multi_rois, restore_t


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pool (reference vision/ops.py:1385
    over phi/kernels/cpu/psroi_pool_kernel.cc). Vectorized as masked
    reductions over the full feature map — static shapes, differentiable."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = (int(v) for v in output_size)
    if ph * pw == 0:
        raise ValueError("output_size should not contain 0.")
    x_t, boxes_t = as_tensor(x), as_tensor(boxes)
    n, c, hgt, wid = (int(d) for d in x_t.shape)
    if c % (ph * pw):
        raise ValueError(
            f"input channels ({c}) must be divisible by output_size "
            f"({ph}x{pw})")
    oc = c // (ph * pw)
    bn = np.asarray(as_tensor(boxes_num).numpy(), np.int64)
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn))

    def f(xa, ba):
        r = ba.shape[0]
        x1 = jnp.round(ba[:, 0]) * spatial_scale
        y1 = jnp.round(ba[:, 1]) * spatial_scale
        x2 = (jnp.round(ba[:, 2]) + 1.0) * spatial_scale
        y2 = (jnp.round(ba[:, 3]) + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh_sz = rh / ph
        bw_sz = rw / pw

        def bin_mask(start, size, total, bins):
            lo = jnp.floor(start[:, None] + jnp.arange(bins)[None, :]
                           * size[:, None])
            hi = jnp.ceil(start[:, None] + (jnp.arange(bins)[None, :] + 1)
                          * size[:, None])
            lo = jnp.clip(lo, 0, total)
            hi = jnp.clip(hi, 0, total)
            pos = jnp.arange(total)[None, None, :]
            m = (pos >= lo[..., None]) & (pos < hi[..., None])
            return m.astype(xa.dtype), jnp.maximum(hi - lo, 0.0)

        mh, ch_ = bin_mask(y1, bh_sz, hgt, ph)     # [R, ph, H], [R, ph]
        mw, cw_ = bin_mask(x1, bw_sz, wid, pw)     # [R, pw, W], [R, pw]
        # per-roi feature slab, channels regrouped [oc, ph, pw]
        feats = xa[img_of_roi].reshape(r, oc, ph, pw, hgt, wid)
        # out[r, o, i, j] = sum_hw feats[r, o, i, j] * mh[r,i,h] * mw[r,j,w]
        s = jnp.einsum("roijhw,rih,rjw->roij", feats, mh, mw)
        area = ch_[:, :, None] * cw_[:, None, :]
        out = jnp.where(area[:, None] > 0, s / jnp.maximum(area[:, None], 1.0),
                        0.0)
        return out

    return apply(f, x_t, boxes_t, name="psroi_pool")


def read_file(filename, name=None):
    """Read raw file bytes into a 1-D uint8 tensor (reference
    vision/ops.py:1294)."""
    with open(filename, "rb") as fh:
        data = np.frombuffer(fh.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference vision/ops.py:1336
    over CPU decode; TPU path decodes on host via PIL)."""
    import io
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg requires Pillow") from e
    raw = bytes(np.asarray(as_tensor(x).numpy(), np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode in ("gray", "grey", "L"):
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]                            # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)               # [C, H, W]
    return Tensor(jnp.asarray(arr))


# --- layer classes ---------------------------------------------------------

from ..nn.layer import Layer  # noqa: E402  (nn does not import vision)
from ..nn.initializer import Normal  # noqa: E402


class DeformConv2D(Layer):
    """Reference vision/ops.py:953."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        if weight_attr is False:
            raise ValueError("weight_attr should not be False in Conv.")
        to2 = lambda v: [v, v] if isinstance(v, int) else list(v)  # noqa: E731
        self._stride = to2(stride)
        self._padding = to2(padding)
        self._dilation = to2(dilation)
        self._kernel_size = to2(kernel_size)
        self._deformable_groups = deformable_groups
        self._groups = groups
        if in_channels % groups:
            raise ValueError("in_channels must be divisible by groups.")
        filter_shape = [out_channels, in_channels // groups] \
            + self._kernel_size
        std = (2.0 / (np.prod(self._kernel_size) * in_channels)) ** 0.5
        self.weight = self.create_parameter(
            shape=filter_shape, attr=weight_attr,
            default_initializer=Normal(0.0, std))
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        from .ops import deform_conv2d
        return deform_conv2d(
            x, offset, self.weight, bias=self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups,
            groups=self._groups, mask=mask)


class RoIAlign(Layer):
    """Reference vision/ops.py:1753."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        from .ops import roi_align
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(Layer):
    """Reference vision/ops.py:1584."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        from .ops import roi_pool
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    """Reference vision/ops.py:1460."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)
