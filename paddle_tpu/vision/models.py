"""Vision models (reference: python/paddle/vision/models/ — resnet.py,
lenet.py). NCHW layout; conv+bn+relu stacks map straight onto the MXU as
implicit-GEMM convolutions."""

from __future__ import annotations

import paddle_tpu as paddle
from .. import nn

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "BasicBlock", "BottleneckBlock"]


class LeNet(nn.Layer):
    """Reference vision/models/lenet.py."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = paddle.flatten(x, 1)
        return self.fc(x)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.conv3 = nn.Conv2D(planes, planes * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """Reference vision/models/resnet.py ResNet."""

    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        layers += [block(self.inplanes, planes) for _ in range(1, blocks)]
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(pretrained=False, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], **kw)
